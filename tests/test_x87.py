"""x87 subset vs the REAL host CPU (VERDICT r3 item 3, 'x87/MMX minimal').

Protocol: operands ride in GPRs, cross into the FPU through stack memory,
and results come back the same way.  Every snippet pins PC=53 via fldcw
(the control word Windows runs with) so the oracle's double-precision
value model is bit-exact against hardware for the whole f64 range; the
host's original control word is restored before returning.
"""

import struct

import pytest

from emurunner import run_emu
from nativeharness import run_native
from wtf_tpu.core.cpustate import GPR_NAMES

F64 = {
    "one5": 0x3FF8000000000000,        # 1.5
    "two25": 0x4002000000000000,       # 2.25
    "neg42": 0xC045000000000000,
    "pi": 0x400921FB54442D18,
    "e": 0x4005BF0A8B145769,
    "pzero": 0x0000000000000000,
    "nzero": 0x8000000000000000,
    "pinf": 0x7FF0000000000000,
    "ninf": 0xFFF0000000000000,
    "qnan": 0x7FF8000000005678,
    "denorm": 0x0000000000000001,
    "tiny": 0x0010000000000000,
    "big": 0x7FE0123456789ABC,
}

_PRELUDE = """
    sub rsp, 40
    fnstcw [rsp+32]               # save the host control word
    mov word ptr [rsp+34], 0x27F  # PC=53, all exceptions masked
    fldcw [rsp+34]
    mov [rsp], rax
    mov [rsp+8], rcx
"""
_EPILOGUE = """
    fldcw [rsp+32]                # restore the host control word
    add rsp, 40
"""


def _run_both(snippet, init_regs):
    init = [0] * 16
    for name, value in init_regs.items():
        init[GPR_NAMES.index(name)] = value
    hw_regs, hw_flags = run_native(snippet, init)
    regs = {n: v for n, v in zip(GPR_NAMES, init) if n != "rsp"}
    cpu = run_emu(snippet + "\nhlt", regs=regs)
    return hw_regs, hw_flags, cpu


@pytest.mark.parametrize("body", [
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfaddp st(1), st",
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfsubp st(1), st",
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfsubrp st(1), st",
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfmulp st(1), st",
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfdivp st(1), st",
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfdivrp st(1), st",
    "fld qword ptr [rsp]\nfadd qword ptr [rsp+8]",
    "fld qword ptr [rsp]\nfmul qword ptr [rsp+8]",
    "fld qword ptr [rsp]\nfdiv qword ptr [rsp+8]",
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfadd st, st(1)\n"
    "fstp st(1)",
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfxch\nfsubp st(1), st",
    "fld qword ptr [rsp]\nfchs",
    "fld qword ptr [rsp]\nfabs",
    "fld1\nfld qword ptr [rsp]\nfaddp st(1), st",
    "fldz\nfld qword ptr [rsp]\nfsubp st(1), st",
])
@pytest.mark.parametrize("a_name,b_name", [
    ("one5", "two25"), ("pi", "e"), ("neg42", "one5"), ("big", "tiny"),
    ("pinf", "ninf"), ("qnan", "one5"), ("pzero", "nzero"),
    ("denorm", "denorm"),
])
def test_x87_arith_vs_hardware(body, a_name, b_name):
    snippet = (_PRELUDE + body
               + "\nfstp qword ptr [rsp+16]\nmov rax, [rsp+16]"
               + _EPILOGUE)
    hw_regs, _, cpu = _run_both(
        snippet, {"rax": F64[a_name], "rcx": F64[b_name]})
    assert cpu.gpr[0] == hw_regs[0], (
        f"{body.splitlines()[-1]}({a_name},{b_name}): "
        f"emu={cpu.gpr[0]:#018x} hw={hw_regs[0]:#018x}")


@pytest.mark.parametrize("ival", [0, 1, -1 & (1 << 64) - 1, 123456789,
                                  0xFFFFFFFF00000000, 1 << 52])
def test_fild_fistp_vs_hardware(ival):
    snippet = (_PRELUDE
               + "fild qword ptr [rsp]\nfistp qword ptr [rsp+16]\n"
               + "mov rax, [rsp+16]" + _EPILOGUE)
    hw_regs, _, cpu = _run_both(snippet, {"rax": ival})
    assert cpu.gpr[0] == hw_regs[0], f"{ival:#x}"


@pytest.mark.parametrize("a_name,b_name", [
    ("one5", "two25"), ("two25", "one5"), ("one5", "one5"),
    ("qnan", "one5"), ("pinf", "big"),
])
def test_fcomi_and_fnstsw_vs_hardware(a_name, b_name):
    snippet = (_PRELUDE + """
    fld qword ptr [rsp+8]
    fld qword ptr [rsp]
    fcomip st, st(1)
    pushfq
    pop r8                        # flags BEFORE the epilogue's add rsp
    fstp st(0)
    fld qword ptr [rsp+8]
    fld qword ptr [rsp]
    fucompp
    fnstsw ax
    movzx rdx, ax
    and rdx, 0x4700
""" + _EPILOGUE)
    hw_regs, hw_flags, cpu = _run_both(
        snippet, {"rax": F64[a_name], "rcx": F64[b_name]})
    mask = 0x8D5
    assert cpu.gpr[8] & mask == hw_regs[8] & mask, (
        f"fcomip({a_name},{b_name}): emu={cpu.gpr[8]:#x} hw={hw_regs[8]:#x}")
    assert cpu.gpr[2] == hw_regs[2], (
        f"fnstsw C-codes: emu={cpu.gpr[2]:#x} hw={hw_regs[2]:#x}")


def test_fxsave_fxrstor_roundtrip():
    """FXSAVE writes the real 512-byte image (control words, abridged tag,
    80-bit ST slots, XMM0-15); FXRSTOR restores it — the context-switch
    path real ntoskrnl images hit (oracle-level; the image layout itself
    is the contract)."""
    from emurunner import run_emu

    area = 0x2000_0000
    cpu = run_emu(
        f"""
        mov rbx, {area}
        mov rax, 0x3FF8000000000000
        mov [rbx+0x600], rax
        fld qword ptr [rbx+0x600]     # st0 = 1.5
        mov rax, 0x1122334455667788
        movq xmm5, rax
        fxsave [rbx]
        fstp st(0)                    # clobber the FPU...
        fldz
        fstp st(0)
        pxor xmm5, xmm5               # ...and xmm5
        fxrstor [rbx]                 # bring everything back
        fstp qword ptr [rbx+0x608]
        mov rax, [rbx+0x608]
        movq rcx, xmm5
        hlt
        """,
        data={area: bytes(0x1000)})
    assert cpu.gpr[0] == 0x3FF8000000000000   # st0 survived the roundtrip
    assert cpu.gpr[1] == 0x1122334455667788   # xmm5 too
    # saved image: fcw at +0, abridged tag nonzero, st0 as 80-bit at +32
    img = cpu.virt_read(area, 512)
    fcw = struct.unpack_from("<H", img, 0)[0]
    assert fcw in (0x27F, 0x37F)
    assert img[4] != 0
    v80 = int.from_bytes(img[32:42], "little")
    assert v80 >> 64 == 0x3FFF                # exponent of 1.5
    assert img[160 + 16 * 5:160 + 16 * 5 + 8] == bytes.fromhex(
        "8877665544332211")


def test_ldmxcsr_stmxcsr_move_real_state():
    low = 0x2000_0000
    cpu = run_emu(
        f"""
        mov rbx, {low}
        mov dword ptr [rbx], 0x9FC0   # FZ|DAZ-ish pattern
        ldmxcsr [rbx]
        stmxcsr [rbx+4]
        mov eax, [rbx+4]
        hlt
        """,
        data={low: bytes(16)})
    assert cpu.gpr[0] == 0x9FC0
    assert cpu.mxcsr == 0x9FC0


@pytest.mark.parametrize("rc,name", [(0, "nearest"), (1, "down"),
                                     (2, "up"), (3, "chop")])
@pytest.mark.parametrize("val_bits", [
    0x4005999999999999,   # 2.7
    0xC005999999999999,   # -2.7
    0x4004000000000000,   # 2.5 (ties: nearest-even -> 2)
    0x400C000000000000,   # 3.5 (ties -> 4)
])
def test_fistp_honors_rounding_control(rc, name, val_bits):
    """fist(p) must honor fpcw.RC — the pre-SSE truncation idiom rewrites
    RC around the store (code-review r4 finding)."""
    cw = 0x27F | (rc << 10)
    snippet = (f"""
    sub rsp, 40
    fnstcw [rsp+32]
    mov word ptr [rsp+34], {cw:#x}
    fldcw [rsp+34]
    mov [rsp], rax
    fld qword ptr [rsp]
    fistp qword ptr [rsp+16]
    mov rax, [rsp+16]
    fldcw [rsp+32]
    add rsp, 40
""")
    hw_regs, _, cpu = _run_both(snippet, {"rax": val_bits})
    assert cpu.gpr[0] == hw_regs[0], (
        f"RC={name} {val_bits:#x}: emu={cpu.gpr[0]:#x} hw={hw_regs[0]:#x}")


def test_80bit_fpst_snapshot_loads_correctly():
    """A snapshot whose fpst carries live 80-bit extended values (real
    bdump dumps) must reduce to the right doubles, not keep the raw low
    64 mantissa bits (code-review r4 finding)."""
    import json
    import tempfile
    from pathlib import Path

    from wtf_tpu.core.cpustate import load_cpu_state_json
    from wtf_tpu.cpu.emu import _f80_to_f64_bits

    f80_15 = 0x3FFFC000000000000000          # 1.5 in 80-bit extended
    f80_neg = 0xC000A000000000000000          # -2.5
    with tempfile.TemporaryDirectory() as tmp:
        p = Path(tmp) / "regs.json"
        p.write_text(json.dumps({
            "rip": "0x1000", "fptw": "0x0",
            "fpst": [hex(f80_15), hex(f80_neg)] + ["0x0"] * 6,
        }))
        state = load_cpu_state_json(p)
    assert state.fpst[0] == f80_15            # parse keeps full precision
    assert _f80_to_f64_bits(f80_15) == 0x3FF8000000000000
    assert _f80_to_f64_bits(f80_neg) == 0xC004000000000000
    # the oracle reduces on load
    from emurunner import build_guest
    from wtf_tpu.cpu.emu import EmuCpu, EmuMem
    from wtf_tpu.mem.physmem import PhysMem

    physmem, cpustate, _ = build_guest("nop\nhlt")
    cpustate.fpst = [f80_15, f80_neg] + [0] * 6
    cpu = EmuCpu(EmuMem(physmem), cpustate)
    assert cpu.fpst[0] == 0x3FF8000000000000
    assert cpu.fpst[1] == 0xC004000000000000
    # and the device machine broadcast does the same reduction
    from wtf_tpu.interp.machine import _fpst_f64_bits

    assert _fpst_f64_bits(f80_15) == 0x3FF8000000000000


def test_vex_three_op_degenerate_forms_decode():
    """VEX src1==dst degenerate encodings MSVC /arch:AVX emits
    (code-review r4 finding): scalar converts, vmovlps loads, vpslldq."""
    import sys
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from asmhelper import assemble
    from wtf_tpu.cpu.decoder import decode
    from wtf_tpu.cpu.uops import OPC_INVALID, OPC_SSEALU, OPC_SSEFP, \
        OPC_SSEMOV

    pad = b"\x90" * 12
    assert decode(assemble("vcvtsd2ss xmm1, xmm1, xmm2") + pad).opc \
        == OPC_SSEFP
    assert decode(assemble("vcvtss2sd xmm3, xmm3, [rax]") + pad).opc \
        == OPC_SSEFP
    assert decode(assemble("vmovlps xmm1, xmm1, [rax]") + pad).opc \
        == OPC_SSEMOV
    assert decode(assemble("vmovhps xmm2, xmm2, [rbx]") + pad).opc \
        == OPC_SSEMOV
    assert decode(assemble("vpslldq xmm4, xmm4, 3") + pad).opc == OPC_SSEALU
    assert decode(assemble("vpsrldq xmm9, xmm9, 5") + pad).opc == OPC_SSEALU
    # non-degenerate 3-operand forms stay rejected
    assert decode(assemble("vcvtsd2ss xmm1, xmm2, xmm3") + pad).opc \
        == OPC_INVALID
    assert decode(assemble("vpslldq xmm4, xmm5, 3") + pad).opc == OPC_INVALID


def test_xsave_xrstor_context_switch_shape():
    """XSAVE64/XRSTOR64 with RFBM=edx:eax — the ntoskrnl context-switch
    idiom: save x87+SSE, clobber, restore; then a partial restore (SSE
    only) leaves the clobbered x87 in the init state."""
    area = 0x2000_0000
    cpu = run_emu(
        f"""
        mov rbx, {area}
        mov rax, 0x4008000000000000
        mov [rbx+0x700], rax
        fld qword ptr [rbx+0x700]     # st0 = 3.0
        mov rax, 0xA1B2C3D4E5F60718
        movq xmm9, rax
        mov eax, 3                    # RFBM = x87|SSE
        xor edx, edx
        xsave [rbx]
        fstp st(0)
        fldz
        fstp st(0)                    # wreck x87
        pxor xmm9, xmm9               # wreck xmm9
        mov eax, 3
        xsave [rbx+0x800]             # capture the wrecked state too
        mov eax, 3
        xor edx, edx
        xrstor [rbx]                  # full restore
        fstp qword ptr [rbx+0x708]
        mov rcx, [rbx+0x708]
        movq rdx, xmm9
        mov eax, 2                    # SSE-only restore from the good image
        push rdx
        xor edx, edx
        xrstor [rbx]
        pop rdx
        fnstsw ax                     # x87 untouched by SSE-only restore
        hlt
        """,
        data={area: bytes(0x1000)})
    assert cpu.gpr[1] == 0x4008000000000000   # st0 came back as 3.0
    assert cpu.gpr[2] == 0xA1B2C3D4E5F60718   # xmm9 came back
    # the first XSAVE image header recorded both components
    import struct as s
    assert s.unpack_from("<Q", cpu.virt_read(area + 512, 8), 0)[0] == 3


def _zmm_with_ymm(idx_vals):
    zmm = [[0] * 8 for _ in range(32)]
    for idx, (lo, hi) in idx_vals.items():
        zmm[idx][2], zmm[idx][3] = lo, hi
    return zmm


def test_ymm_state_carries_through_xsave_avx():
    """VERDICT r4 item 5: a snapshot captured with live YMM state must
    round-trip — the upper halves ride CpuState.zmm into the machine, the
    xsave AVX component (RFBM bit 2, standard offset 576) services them,
    and vzeroupper/xrstor behave architecturally."""
    area = 0x2000_0000
    ymm = {3: (0x1111222233334444, 0x5555666677778888),
           12: (0xAAAABBBBCCCCDDDD, 0x0123456789ABCDEF)}
    cpu = run_emu(
        f"""
        mov rbx, {area}
        mov eax, 7                    # RFBM = x87|SSE|AVX
        xor edx, edx
        xsave [rbx]                   # writes the AVX component
        vzeroupper                    # clears ONLY the upper halves
        mov eax, 4
        xor edx, edx
        xsave [rbx+0x800]             # AVX-only image of cleared state
        mov eax, 4
        xor edx, edx
        xrstor [rbx]                  # bring the upper halves back
        hlt
        """,
        data={area: bytes(0x1000)},
        regs={"zmm": _zmm_with_ymm(ymm)})
    import struct as s

    # first image: AVX component saved at offset 576, XSTATE_BV bit 2 set
    assert s.unpack_from("<Q", cpu.virt_read(area + 512, 8), 0)[0] & 4
    lo, hi = s.unpack_from("<QQ", cpu.virt_read(area + 576 + 16 * 3, 16), 0)
    assert (lo, hi) == ymm[3]
    lo, hi = s.unpack_from("<QQ", cpu.virt_read(area + 576 + 16 * 12, 16), 0)
    assert (lo, hi) == ymm[12]
    # second image captured the vzeroupper-cleared state
    lo, hi = s.unpack_from(
        "<QQ", cpu.virt_read(area + 0x800 + 576 + 16 * 3, 16), 0)
    assert (lo, hi) == (0, 0)
    # xrstor restored the original upper halves
    assert cpu.ymmh[3] == list(ymm[3])
    assert cpu.ymmh[12] == list(ymm[12])


def test_ymm_state_device_round_trip():
    """The device machine carries the upper YMM limbs untouched through
    SSE execution, and vzeroupper/vzeroall execute ON DEVICE (no oracle
    fallback) with the architectural split."""
    import sys
    sys.path.insert(0, "tests")
    from test_step import assert_matches_oracle, make_runner
    from wtf_tpu.core.results import StatusCode

    ymm = _zmm_with_ymm({1: (0xDEAD, 0xBEEF), 15: (0x77, 0x88)})
    # legacy SSE writes to xmm1 must preserve its upper YMM half
    assert_matches_oracle(
        "movq xmm1, rax\npaddq xmm1, xmm1\nmovq rbx, xmm1\nhlt",
        regs={"rax": 21, "zmm": ymm})
    # vzeroupper on device: uppers cleared, xmm preserved, zero fallbacks
    runner = make_runner(
        "movq xmm1, rax\nvzeroupper\nmovq rbx, xmm1\nhlt",
        regs={"rax": 42, "zmm": ymm})
    status = runner.run()
    assert all(StatusCode(int(s)) == StatusCode.CRASH for s in status)
    assert runner.stats["fallbacks"] == 0
    import numpy as np
    xmm = np.asarray(runner.machine.xmm)
    assert int(xmm[0, 1, 0]) == 42          # xmm kept
    assert int(xmm[0, 1, 2]) == 0           # upper half cleared
    assert int(xmm[0, 15, 2]) == 0
