"""Device-executor differential tests: interp/step.py vs the EmuCpu oracle.

The decoder is shared between both paths, so every assertion here pins down
exactly one thing: that the JAX transition function implements the same
*semantics* per uop as cpu/emu.py (which itself is pinned to real hardware
by tests/test_emu.py's hw-differential suite).  This is the rebuild's
version of the reference's cross-backend trace-diffing methodology
(SURVEY.md §4.3: develop on deterministic bochscpu, validate fast backends
against its traces).
"""

import numpy as np
import pytest

from tests.emurunner import CODE_BASE, DATA_BASE, STACK_TOP, build_guest, run_emu
from tests.test_emu import HW_CASES, _ALT_REGS, _INIT_REGS
from wtf_tpu.core.cpustate import GPR_NAMES
from wtf_tpu.core.results import StatusCode
from wtf_tpu.interp.runner import Runner
from wtf_tpu.snapshot.loader import Snapshot

# Flags the device path intentionally models (TF/IF excluded: test guests
# don't exercise interrupt masking beyond FLAGOP, which both paths share).
RF_CMP = 0x8D5 | 0x400


def make_runner(asm, data=None, regs=None, n_lanes=2, limit=0):
    physmem, cpustate, _ = build_guest(asm, data)
    if regs:
        for name, value in regs.items():
            setattr(cpustate, name, value)
    snap = Snapshot(physmem=physmem, cpu=cpustate)
    runner = Runner(snap, n_lanes=n_lanes, chunk_steps=64)
    runner.limit = limit
    return runner


def run_tpu(asm, data=None, regs=None, n_lanes=2, limit=0):
    runner = make_runner(asm, data, regs, n_lanes, limit)
    status = runner.run()
    return runner, status


def assert_matches_oracle(asm, data=None, regs=None, n_lanes=2,
                          check_mem=True):
    """Run `asm` (must end in hlt) on both engines; compare the complete
    device-resident architectural state and all dirty memory."""
    emu = run_emu(asm, data=data, regs=regs)
    runner, status = run_tpu(asm, data=data, regs=regs, n_lanes=n_lanes)

    for lane in range(n_lanes):
        assert StatusCode(int(status[lane])) == StatusCode.CRASH, (
            f"lane {lane}: {StatusCode(int(status[lane])).name}, "
            f"errors={runner.lane_errors}")
    g = np.asarray(runner.machine.gpr)
    rf = np.asarray(runner.machine.rflags)
    rip = np.asarray(runner.machine.rip)
    xmm = np.asarray(runner.machine.xmm)
    for lane in range(n_lanes):
        for i, name in enumerate(GPR_NAMES):
            assert int(g[lane, i]) == emu.gpr[i], (
                f"lane {lane} {name}: tpu={int(g[lane, i]):#x} "
                f"emu={emu.gpr[i]:#x}")
        assert int(rf[lane]) & RF_CMP == emu.rflags & RF_CMP, (
            f"lane {lane} rflags: tpu={int(rf[lane]):#x} emu={emu.rflags:#x}")
        assert int(rip[lane]) == emu.rip
        for i in range(16):
            assert int(xmm[lane, i, 0]) == emu.xmm[i][0], f"xmm{i} lo"
            assert int(xmm[lane, i, 1]) == emu.xmm[i][1], f"xmm{i} hi"
            assert int(xmm[lane, i, 2]) == emu.ymmh[i][0], f"ymm{i} up lo"
            assert int(xmm[lane, i, 3]) == emu.ymmh[i][1], f"ymm{i} up hi"
        fpst = np.asarray(runner.machine.fpst)
        for p in range(8):
            assert int(fpst[lane, p]) == emu.fpst[p], (
                f"lane {lane} fpst[{p}]: tpu={int(fpst[lane, p]):#x} "
                f"emu={emu.fpst[p]:#x}")
        assert int(runner.machine.fpsw[lane]) & 0xFFFF == emu.fpsw_packed()
        assert int(runner.machine.fptw[lane]) & 0xFFFF == emu.fptw
        assert int(runner.machine.fpcw[lane]) & 0xFFFF == emu.fpcw
    if check_mem:
        view = runner.view()
        for pfn in emu.mem.dirty_pfns():
            want = bytes(emu.mem.overlay[pfn])
            for lane in range(n_lanes):
                got = view.page(lane, pfn)
                assert got == want, (
                    f"lane {lane} page {pfn:#x} diverges at offset "
                    f"{next(i for i in range(4096) if got[i] != want[i])}")
    return runner, emu


# ---------------------------------------------------------------------------
# 1. the full hardware-differential corpus, now three-way:
#    hardware (via test_emu) == oracle == device
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,snippet,fmask",
                         [(c[0], c[1], c[2]) for c in HW_CASES])
@pytest.mark.parametrize("initset", ["a", "b"])
def test_device_vs_oracle_hw_cases(name, snippet, fmask, initset):
    init = list(_INIT_REGS if initset == "a" else _ALT_REGS)
    regs = {n: v for n, v in zip(GPR_NAMES, init)}
    regs.pop("rsp")
    assert_matches_oracle(snippet + "\nhlt", regs=regs)


# ---------------------------------------------------------------------------
# 2. memory / control flow / strings / SSE snippets
# ---------------------------------------------------------------------------

DIFF_CASES = [
    ("mem_load_store", f"""
        mov rbx, {DATA_BASE}
        mov r9, 0x1122334455667788
        mov [rbx], r9
        mov rcx, [rbx]
        mov [rbx+8], ecx
        mov dx, [rbx+8]
        mov byte ptr [rbx+0x10], 0x7F
        movzx rsi, byte ptr [rbx+0x10]
        hlt""", {DATA_BASE: b"\x00" * 0x1000}),
    ("page_crossing", f"""
        mov rbx, {DATA_BASE + 0xFFC}
        mov rax, 0x0123456789ABCDEF
        mov [rbx], rax
        mov rcx, [rbx]
        hlt""", {DATA_BASE: b"\x00" * 0x2000}),
    ("rip_relative", """
        lea rax, [rip + data_here]
        mov rbx, [rip + data_here]
        hlt
        data_here: .quad 0xFEEDFACECAFEBEEF""", None),
    ("call_ret", """
        call func
        add rax, 1
        hlt
        func:
        mov rax, 41
        ret""", None),
    ("fib_loop", """
        mov rax, 0
        mov rbx, 1
        mov rcx, 20
        fib:
        mov rdx, rax
        add rax, rbx
        mov rbx, rdx
        dec rcx
        jnz fib
        hlt""", None),
    ("rep_movsb", f"""
        mov rsi, {DATA_BASE}
        mov rdi, {DATA_BASE + 0x800}
        mov rcx, 300
        rep movsb
        mov al, [rdi-1]
        hlt""", {DATA_BASE: bytes(range(256)) + b"\xAB" * 44 + b"\x00" * 0x700}),
    ("rep_stosq_scasb", f"""
        mov rdi, {DATA_BASE}
        mov rax, 0x4141414141414141
        mov rcx, 32
        rep stosq
        mov rdi, {DATA_BASE}
        mov al, 0x42
        mov rcx, 512
        repne scasb
        hlt""", {DATA_BASE: b"\x00" * 0x1000}),
    ("repe_cmpsb", f"""
        mov rsi, {DATA_BASE}
        mov rdi, {DATA_BASE + 0x100}
        mov rcx, 64
        repe cmpsb
        hlt""",
     {DATA_BASE: b"same-prefix-data" * 2 + b"X" + b"\x00" * 0xD0
      + b"same-prefix-data" * 2 + b"Y" + b"\x00" * 0xD0}),
    ("movs_df_backwards", f"""
        mov rsi, {DATA_BASE + 0x78}
        mov rdi, {DATA_BASE + 0x178}
        mov rcx, 16
        std
        rep movsq
        cld
        hlt""", {DATA_BASE: bytes((i * 7) & 0xFF for i in range(0x200))}),
    ("jcc_spectrum", """
        xor r15, r15
        mov rax, 5
        cmp rax, 5
        je l1
        or r15, 1
        l1:
        cmp rax, 6
        jb l2
        or r15, 2
        l2:
        cmp rax, 4
        jg l3
        or r15, 4
        l3:
        test rax, rax
        js l4
        or r15, 8
        l4:
        hlt""", None),
    ("jrcxz", """
        mov rcx, 1
        jrcxz skip1
        mov rax, 1
        xor rcx, rcx
        jrcxz skip2
        mov rax, 99
        skip2:
        skip1:
        hlt""", None),
    ("fsgsbase_ops", """
        mov rax, 0x5678DEADBEEF
        wrfsbase rax
        rdfsbase rbx
        wrgsbase rax
        rdgsbase rcx
        mov esi, 0xCAFE0000
        wrfsbase esi
        rdfsbase rdx
        rdfsbase r8d
        hlt""", None),
    ("enter_leave_roundtrip", """
        mov rbp, 0x1122334455667788
        mov rdi, rsp
        enter 0x40, 0
        mov rax, rbp
        mov rbx, [rbp]
        lea rcx, [rbp-0x40]
        leave
        mov rdx, rsp
        hlt""", None),
    ("msr_roundtrip", """
        mov ecx, 0xC0000082
        mov eax, 0x11223344
        mov edx, 0x55667788
        wrmsr
        xor eax, eax
        xor edx, edx
        rdmsr
        mov rbx, rax
        mov rsi, rdx
        mov ecx, 0xC0000101
        rdmsr
        mov r8, rax
        mov ecx, 0xC0000080
        rdmsr
        hlt""", None),
    ("wrmsr_lstar_steers_syscall", """
        lea rax, [rip + handler]
        mov rdx, rax
        shr rdx, 32
        mov ecx, 0xC0000082
        wrmsr
        syscall
        mov rbx, 0xBAD
        hlt
    handler:
        mov rbx, 0x600D
        hlt""", None),
    ("jecxz_a32", """
        mov rcx, 0xF00000000
        jecxz taken
        mov rax, 99
        taken:
        mov rbx, 7
        mov ecx, 1
        jecxz bad
        mov rbx, 1
        bad:
        hlt""", None),
    ("push_imm_leave", """
        push 0x1234
        pop rax
        push rbp
        mov rbp, rsp
        sub rsp, 0x20
        mov qword ptr [rbp-8], 0x77
        mov rbx, [rbp-8]
        leave
        hlt""", None),
    ("bt_mem_bitstring", f"""
        mov rbx, {DATA_BASE}
        mov rax, 100
        bts [rbx], rax
        mov rax, -9
        bts qword ptr [rbx+0x40], rax
        mov rcx, 100
        bt [rbx], rcx
        setc dl
        hlt""", {DATA_BASE: b"\x00" * 0x1000}),
    ("xchg_mem", f"""
        mov rbx, {DATA_BASE}
        mov qword ptr [rbx], 0x1111
        mov rax, 0x2222
        xchg [rbx], rax
        hlt""", {DATA_BASE: b"\x00" * 0x1000}),
    ("sse_roundtrip_pxor", f"""
        mov rbx, {DATA_BASE}
        movdqu xmm0, [rbx]
        movdqu xmm1, [rbx+16]
        pxor xmm0, xmm1
        movdqu [rbx+32], xmm0
        movdqa xmm2, xmm0
        por xmm2, xmm1
        hlt""", {DATA_BASE: bytes(range(32)) + b"\x00" * 0x100}),
    ("sse_punpckldq_paddq", f"""
        mov rbx, {DATA_BASE}
        movdqu xmm0, [rbx]
        movdqu xmm1, [rbx+16]
        punpckldq xmm0, xmm1
        movdqu xmm2, [rbx]
        paddq xmm2, xmm1
        paddq xmm2, [rbx+16]
        movdqu [rbx+32], xmm0
        movdqu [rbx+48], xmm2
        hlt""", {DATA_BASE: bytes(range(200, 232)) + b"\x00" * 0x100}),
    ("sse_pinsrw_pextrw", f"""
        mov rbx, {DATA_BASE}
        movdqu xmm0, [rbx]
        mov eax, 0xBEEF
        pinsrw xmm0, eax, 3
        pinsrw xmm0, eax, 7
        pextrw ecx, xmm0, 3
        pextrw edx, xmm0, 0
        movdqu [rbx+32], xmm0
        hlt""", {DATA_BASE: bytes(range(64)) + b"\x00" * 0x100}),
    ("sse_psllq_psrlq_imm", f"""
        mov rbx, {DATA_BASE}
        movdqu xmm0, [rbx]
        psllq xmm0, 5
        movdqu xmm1, [rbx]
        psrlq xmm1, 23
        movdqu xmm2, [rbx]
        psrlq xmm2, 64
        movdqu xmm3, [rbx]
        psllq xmm3, 63
        movdqu [rbx+32], xmm0
        movdqu [rbx+48], xmm1
        hlt""", {DATA_BASE: bytes(range(100, 132)) + b"\x00" * 0x100}),
    ("sse_movlps_movhps", f"""
        mov rbx, {DATA_BASE}
        movdqu xmm0, [rbx]
        movlps xmm0, [rbx+16]
        movhps xmm0, [rbx+24]
        movdqu xmm1, [rbx+32]
        movhlps xmm1, xmm0
        movlhps xmm1, xmm0
        movlps [rbx+48], xmm0
        movhps [rbx+56], xmm1
        hlt""", {DATA_BASE: bytes(range(64, 128)) + b"\x00" * 0x100}),
    ("sse_movq_movd", f"""
        mov rax, 0x1122334455667788
        movq xmm0, rax
        movd xmm1, eax
        movq rbx, xmm0
        movd ecx, xmm1
        pcmpeqb xmm2, xmm2
        pmovmskb edx, xmm2
        hlt""", None),
    ("sse_scalar_merge", f"""
        mov rbx, {DATA_BASE}
        movdqu xmm0, [rbx]
        movss xmm1, [rbx+4]
        movsd xmm2, [rbx+8]
        movss xmm0, xmm1
        movaps xmm3, xmm0
        hlt""", {DATA_BASE: bytes(range(64)) + b"\x00" * 0x100}),
    ("string_single_ops", f"""
        mov rsi, {DATA_BASE}
        mov rdi, {DATA_BASE + 0x20}
        lodsq
        stosq
        movsb
        mov rdi, {DATA_BASE}
        scasb
        hlt""", {DATA_BASE: bytes(range(64)) + b"\x00" * 0x100}),
    ("shift_mem_forms", f"""
        mov rbx, {DATA_BASE}
        mov rdx, 0x8000000000000001
        mov [rbx], rdx
        shl qword ptr [rbx], 3
        mov cl, 5
        shr qword ptr [rbx+8], cl
        sar dword ptr [rbx+16], 2
        rol word ptr [rbx+24], 9
        hlt""",
     {DATA_BASE: b"\xFF" * 32 + b"\x00" * 0x100}),
    ("div_mem_8bit", f"""
        mov rbx, {DATA_BASE}
        mov byte ptr [rbx], 7
        mov ax, 1234
        div byte ptr [rbx]
        mov rcx, 0
        mov rdx, 0
        mov rax, 0xFFFFFFFF
        mov rcx, 16
        div rcx
        hlt""", {DATA_BASE: b"\x00" * 0x1000}),
    ("imul_neg_mem", f"""
        mov rbx, {DATA_BASE}
        mov qword ptr [rbx], -7
        mov rax, 3
        imul qword ptr [rbx]
        imul rcx, rax, -9
        hlt""", {DATA_BASE: b"\x00" * 0x1000}),
    ("cmpxchg_mem", f"""
        mov rbx, {DATA_BASE}
        mov qword ptr [rbx], 5
        mov rax, 5
        mov rcx, 9
        cmpxchg [rbx], rcx
        mov rax, 123
        cmpxchg [rbx], rcx
        hlt""", {DATA_BASE: b"\x00" * 0x1000}),
    ("xadd_inc_dec_mem", f"""
        mov rbx, {DATA_BASE}
        mov qword ptr [rbx], 10
        mov rax, 32
        xadd [rbx], rax
        inc qword ptr [rbx]
        dec word ptr [rbx+8]
        neg dword ptr [rbx+16]
        not byte ptr [rbx+24]
        hlt""", {DATA_BASE: b"\x11" * 32 + b"\x00" * 0x100}),
]


@pytest.mark.parametrize("name,snippet,data",
                         [(c[0], c[1], c[2]) for c in DIFF_CASES])
def test_device_vs_oracle_mem_cases(name, snippet, data):
    assert_matches_oracle(snippet, data=data)


def test_wrfsbase_noncanonical_faults():
    """Hardware #GPs on a non-canonical wr{fs,gs}base source; both
    engines surface it through the non-canonical fault seam (review
    fix) instead of silently loading the base."""
    runner = make_runner(
        "mov rax, 0x8000000000000000\nwrfsbase rax\nhlt", n_lanes=2)
    status = runner.run()
    for lane in range(2):
        assert StatusCode(int(status[lane])) == StatusCode.PAGE_FAULT
        assert int(np.asarray(runner.machine.fault_gva)[lane]) \
            == 0x8000000000000000
    # the base must NOT have been loaded
    assert int(np.asarray(runner.machine.fs_base)[0]) == 0


def test_syscall_transition():
    asm = """
    mov r14, 0x123
    syscall
    hlt
    .org 0x40
    mov rax, 0x5CA11
    hlt
    """
    pad = CODE_BASE + 0x40
    emu = run_emu(asm, regs={"lstar": pad, "sfmask": 0x700})
    runner, status = run_tpu(asm, regs={"lstar": pad, "sfmask": 0x700})
    g = np.asarray(runner.machine.gpr)
    assert int(np.asarray(runner.machine.rip)[0]) == emu.rip
    assert int(g[0, 1]) == emu.gpr[1]    # rcx = return rip
    assert int(g[0, 11]) == emu.gpr[11]  # r11 = saved rflags
    rf = np.asarray(runner.machine.rflags)
    assert int(rf[0]) & RF_CMP == emu.rflags & RF_CMP


def test_rdrand_deterministic():
    asm = "rdrand rax\nrdrand rbx\nrdrand rcx\nhlt"
    runner, _ = run_tpu(asm)
    emu = run_emu(asm)
    g = np.asarray(runner.machine.gpr)
    for lane in range(2):
        assert int(g[lane, 0]) == emu.gpr[0]
        assert int(g[lane, 3]) == emu.gpr[3]
        assert int(g[lane, 1]) == emu.gpr[1]


# ---------------------------------------------------------------------------
# 3. batch semantics: divergent lanes, limits, restore
# ---------------------------------------------------------------------------

def test_divergent_lanes():
    """Each lane branches on its own input -> different paths, different
    results, all correct (the core lockstep-with-masking property)."""
    asm = f"""
    mov rbx, {DATA_BASE}
    mov rax, [rbx]
    cmp rax, 4
    jb small
    mov rcx, 0xB1B
    jmp done
    small:
    mov rcx, 0xA1A
    done:
    imul rdx, rax, 100
    hlt
    """
    runner = make_runner(asm, data={DATA_BASE: b"\x00" * 0x1000}, n_lanes=8)
    view = runner.view()
    for lane in range(8):
        view.virt_write(lane, DATA_BASE, lane.to_bytes(8, "little"))
    runner.push(view)
    status = runner.run()
    g = np.asarray(runner.machine.gpr)
    for lane in range(8):
        assert StatusCode(int(status[lane])) == StatusCode.CRASH
        want_rcx = 0xA1A if lane < 4 else 0xB1B
        assert int(g[lane, 1]) == want_rcx, f"lane {lane}"
        assert int(g[lane, 2]) == lane * 100
    # divergent lanes produce divergent coverage bitmaps
    cov = np.asarray(runner.machine.cov)
    assert not np.array_equal(cov[0], cov[7])


def test_instruction_limit_timeout():
    runner, status = run_tpu("spin: jmp spin", n_lanes=2, limit=500)
    for lane in range(2):
        assert StatusCode(int(status[lane])) == StatusCode.TIMEDOUT
    icount = np.asarray(runner.machine.icount)
    assert int(icount[0]) == 500


def test_restore_roundtrip_batch():
    asm = f"""
    mov rbx, {DATA_BASE}
    add qword ptr [rbx], 1
    mov rax, [rbx]
    hlt
    """
    runner = make_runner(asm, data={DATA_BASE: b"\x00" * 0x1000}, n_lanes=4)
    runner.run()
    first = int(np.asarray(runner.machine.gpr)[0, 0])
    runner.restore()
    runner.run()
    second = int(np.asarray(runner.machine.gpr)[0, 0])
    assert first == second == 1  # memory rolled back between runs
    runner.restore()
    ov = runner.machine.overlay
    assert int(np.asarray(ov.count)[0]) == 0


def test_page_fault_reported():
    runner, status = run_tpu("mov rax, [0x1234]\nhlt", n_lanes=1)
    assert StatusCode(int(status[0])) == StatusCode.PAGE_FAULT
    assert int(np.asarray(runner.machine.fault_gva)[0]) == 0x1234


def test_divide_error_reported():
    runner, status = run_tpu("xor rcx, rcx\nmov rax, 5\ndiv rcx\nhlt",
                             n_lanes=1)
    assert StatusCode(int(status[0])) == StatusCode.DIVIDE_ERROR


def test_div128_host_fallback():
    """64-bit div with rdx != 0 exceeds the device path -> oracle fallback."""
    asm = """
    mov rdx, 1
    mov rax, 0
    mov rcx, 16
    div rcx
    hlt
    """
    runner, status = run_tpu(asm, n_lanes=2)
    emu = run_emu(asm)
    assert runner.stats["fallbacks"] >= 1
    g = np.asarray(runner.machine.gpr)
    for lane in range(2):
        assert StatusCode(int(status[lane])) == StatusCode.CRASH
        assert int(g[lane, 0]) == emu.gpr[0] == 0x1000000000000000
        assert int(g[lane, 2]) == emu.gpr[2]


@pytest.mark.parametrize("leaf,subleaf", [
    (0x0, 0),             # vendor string
    (0x1, 0),             # feature bits
    (0x9, 0),             # in-range basic leaf absent from the table
    (0x1234, 0),          # out-of-range basic -> highest basic leaf
    (0x40000000, 0),      # hypervisor range -> zeros
    (0x80000001, 0),      # extended features
    (0x1, 7),             # nonzero subleaf -> (leaf, 0) fallback
])
def test_cpuid_on_device(leaf, subleaf):
    """CPUID executes on the device (no oracle fallback) and matches the
    oracle's table + fallback chain for every class of leaf."""
    asm = f"mov eax, {leaf:#x}\nmov ecx, {subleaf:#x}\ncpuid\nhlt"
    runner, status = run_tpu(asm, n_lanes=2)
    emu = run_emu(asm)
    g = np.asarray(runner.machine.gpr)
    assert runner.stats["fallbacks"] == 0
    for lane in range(2):
        for reg in (0, 1, 2, 3):
            assert int(g[lane, reg]) == emu.gpr[reg], \
                f"gpr{reg}: tpu={int(g[lane, reg]):#x} emu={emu.gpr[reg]:#x}"


def test_coverage_bitmap_matches_unique_rips():
    asm = """
    mov rax, 3
    again:
    dec rax
    jnz again
    hlt
    """
    runner, _ = run_tpu(asm, n_lanes=1)
    cov = np.asarray(runner.machine.cov)[0]
    rips = sorted(runner.cache.rips_of_bits(cov))
    # mov, dec, jnz, hlt = 4 unique rips regardless of loop count
    assert len(rips) == 4
    assert rips[0] == CODE_BASE


def test_edge_bitmap_set_on_branches():
    runner, _ = run_tpu("jmp fwd\nnop\nfwd: hlt", n_lanes=1)
    edge = np.asarray(runner.machine.edge)[0]
    assert edge.sum() > 0


def test_iretq_matches_oracle():
    """iretq is serviced by the per-lane oracle fallback (UNSUPPORTED on
    device, like the reference's bochs-backs-KVM split); end state must
    match a pure-oracle run."""
    from tests.test_emu import IRETQ_ASM

    assert_matches_oracle(IRETQ_ASM)


def test_rdmsr_wrmsr_match_oracle():
    """rdmsr/wrmsr are serviced by the oracle fallback; MSR-backed fields
    written there must round-trip (and reach the device mirror)."""
    asm = """
    mov ecx, 0xC0000082
    rdmsr
    shl rdx, 32
    or rax, rdx
    mov r12, rax
    mov ecx, 0xC0000102
    mov eax, 0x11223344
    mov edx, 0x55667788
    wrmsr
    xor eax, eax
    xor edx, edx
    mov ecx, 0xC0000102
    rdmsr
    hlt
    """
    regs = {"lstar": 0xFFFFF00012345678}
    runner, emu = assert_matches_oracle(asm, regs=regs)
    assert emu.gpr[12] == 0xFFFFF00012345678          # rdmsr read lstar
    assert emu.gpr[0] == 0x11223344                   # wrmsr round-trip lo
    assert emu.gpr[2] == 0x55667788                   # hi
    kgs = np.asarray(runner.machine.kernel_gs_base)
    assert int(kgs[0]) == 0x5566778811223344          # device mirror updated


def test_wrmsr_efer_persists_across_fallbacks():
    """EFER is device-mirrored: a wrmsr through one oracle fallback must be
    visible to a later rdmsr fallback (each fallback rebuilds the oracle
    CPU from the mirror)."""
    asm = """
    mov ecx, 0xC0000080
    rdmsr
    or eax, 0x4000
    wrmsr
    xor eax, eax
    xor edx, edx
    mov ecx, 0xC0000080
    rdmsr
    hlt
    """
    runner, emu = assert_matches_oracle(asm)
    assert emu.gpr[0] & 0x4000
