"""Device-resident mutation engine (wtf_tpu/devmut) tests.

Three layers:
  * engine property tests — the vectorized u32 generator vs the
    authoritative host reference (devmut/hostref.py), bit-for-bit, over
    randomized corpora/seeds, plus the in-bounds/well-formed invariants
    the acceptance criteria name
  * the fused insert seam — Runner.device_insert lands the generated
    bytes + ABI registers exactly where the host insert_testcase would
  * the campaign path — FuzzLoop's devmangle batches on demo_tlv:
    deterministic given a seed, coverage-finding, with the mutate phase
    measured under mutate/device (host share ~ dispatch only)

The coverage-parity-vs-host-mangle comparison runs a real two-campaign
A/B and lives in the slow tier (same policy as pstep's occupancy pair).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from wtf_tpu.devmut import hostref
from wtf_tpu.devmut.corpus import DeviceCorpus
from wtf_tpu.devmut.engine import make_generate

MAX_LEN = 64            # bytes per testcase in the engine-level tests
WORDS = MAX_LEN // 4


def _slab(rng, slots=4, live=3):
    data = np.zeros((slots, WORDS), np.uint32)
    lens = np.zeros((slots,), np.int32)
    weights = np.zeros((slots,), np.uint32)
    for s in range(live):
        n = rng.randint(1, MAX_LEN + 1)
        buf = np.zeros(MAX_LEN, np.uint8)
        buf[:n] = rng.randint(0, 256, n).astype(np.uint8)
        data[s] = buf.view(np.uint32)
        lens[s] = n
        weights[s] = 1 + s
    cumw = np.cumsum(weights, dtype=np.uint64).astype(np.uint32)
    return data, lens, cumw


@pytest.mark.parametrize("seed", [0xDEAD_BEEF_1234, 7, (1 << 64) - 3])
def test_generate_matches_host_reference(seed):
    """The device batch is bit-for-bit the host reference's, every
    testcase is well-formed (1 <= len <= max_len, zero padding past
    len), and enough batches run that ALL 8 mangle ops are exercised."""
    rng = np.random.RandomState(seed & 0xFFFF)
    data, lens, cumw = _slab(rng)
    gen = make_generate(3)
    ops_seen = set()
    for batch in range(4):
        seeds = hostref.lane_seeds(seed, batch, 8)
        d_words, d_lens = gen(jnp.asarray(data), jnp.asarray(lens),
                              jnp.asarray(cumw), jnp.asarray(seeds))
        trace = []
        h_words, h_lens = hostref.host_generate(data, lens, cumw, seeds,
                                                rounds=3, op_trace=trace)
        ops_seen |= set(trace)
        assert (np.asarray(d_lens) == h_lens).all()
        assert (np.asarray(d_words) == h_words).all()
        # well-formed: in-bounds lengths, zero bytes past each length
        assert (h_lens >= 1).all() and (h_lens <= MAX_LEN).all()
        raw = np.ascontiguousarray(h_words).view(np.uint8)
        for lane in range(8):
            assert not raw[lane, h_lens[lane]:].any()
    # 4 batches x 8 lanes x 3 rounds = 96 draws: every op must appear
    assert ops_seen == set(range(hostref.N_OPS)), sorted(ops_seen)


def test_lane_seeds_match_scalar_spec():
    """The vectorized numpy lane-seed stream is bit-exact with the
    scalar splitmix formula (both device and host mirrors consume these
    seeds, so a silent drift here would not be caught downstream)."""
    from wtf_tpu.utils.hashing import MASK64, mix64

    for seed, batch, n in ((0, 0, 4), (0xDEAD_BEEF, 7, 33),
                           ((1 << 64) - 1, 2, 5)):
        got = hostref.lane_seeds(seed, batch, n)
        for lane in range(n):
            want = mix64((seed + hostref.GOLDEN
                          * (batch * n + lane + 1)) & MASK64)
            assert int(got[lane, 0]) == want & 0xFFFFFFFF
            assert int(got[lane, 1]) == want >> 32


def test_generate_deterministic_and_seed_sensitive():
    rng = np.random.RandomState(3)
    data, lens, cumw = _slab(rng)
    gen = make_generate(3)
    args = (jnp.asarray(data), jnp.asarray(lens), jnp.asarray(cumw))
    seeds = hostref.lane_seeds(0x1234, 0, 4)
    w1, l1 = gen(*args, jnp.asarray(seeds))
    w2, l2 = gen(*args, jnp.asarray(seeds))
    assert (np.asarray(w1) == np.asarray(w2)).all()
    assert (np.asarray(l1) == np.asarray(l2)).all()
    seeds2 = hostref.lane_seeds(0x1235, 0, 4)
    w3, _ = gen(*args, jnp.asarray(seeds2))
    assert (np.asarray(w1) != np.asarray(w3)).any()


def test_generate_empty_corpus_synthesizes_fresh():
    """Zero total weight -> the fresh-synthesis path (1..64 stream
    bytes), still bit-exact vs the host reference."""
    data = np.zeros((4, WORDS), np.uint32)
    lens = np.zeros((4,), np.int32)
    cumw = np.zeros((4,), np.uint32)
    seeds = hostref.lane_seeds(99, 0, 6)
    gen = make_generate(3)
    d_words, d_lens = gen(jnp.asarray(data), jnp.asarray(lens),
                          jnp.asarray(cumw), jnp.asarray(seeds))
    h_words, h_lens = hostref.host_generate(data, lens, cumw, seeds, 3)
    assert (np.asarray(d_words) == h_words).all()
    assert (np.asarray(d_lens) == h_lens).all()
    assert (h_lens >= 1).all()


def test_device_corpus_slab_semantics():
    """add/dedup/evict: zero padding in slots, favored entries out-rank
    plain seeds in the cumulative-weight table and survive eviction."""
    c = DeviceCorpus(slots=3, max_len=16)
    assert c.add(b"AAAA")
    assert not c.add(b"AAAA")          # content dup
    assert c.add(b"BBBBBBBB", weight=hostref.FAVOR_WEIGHT)
    assert c.add(b"CC")
    assert len(c) == 3
    # slot 0 bytes zero-padded to the slab width
    assert c._data[0].view(np.uint8)[:4].tobytes() == b"AAAA"
    assert not c._data[0].view(np.uint8)[4:].any()
    cum = c.cumulative_weights()
    assert cum.dtype == np.uint32
    assert list(cum) == [1, 1 + hostref.FAVOR_WEIGHT,
                         2 + hostref.FAVOR_WEIGHT]
    # full: the new entry evicts the LOWEST-weight slot (slot 0), and
    # the favored slot survives
    assert c.add(b"DDDD", weight=2)
    assert c._data[0].view(np.uint8)[:4].tobytes() == b"DDDD"
    assert c._data[1].view(np.uint8)[:8].tobytes() == b"BBBBBBBB"
    # truncation to max_len
    assert c.add(b"E" * 64)
    assert int(c._len[int(np.argmax(c._weight == 1))]) <= 16
    # duplicate re-add with a higher weight upgrades the slot
    c2 = DeviceCorpus(slots=2, max_len=16)
    c2.add(b"XX")
    assert not c2.add(b"XX", weight=hostref.FAVOR_WEIGHT)
    assert int(c2._weight[0]) == hostref.FAVOR_WEIGHT
    # device arrays re-upload only when dirtied
    _, _, _, synced = c2.arrays()
    assert synced
    _, _, _, synced = c2.arrays()
    assert not synced


def test_device_insert_seam_matches_host_insert():
    """Runner.device_insert writes exactly what demo_tlv's host
    insert_testcase would: bytes at INPUT_GVA through the lane's memory
    view, pointer in rsi, length in rdx — and host page writes to the
    same page still work afterwards (the overlay row is claimed, not
    leaked)."""
    from wtf_tpu.harness import demo_tlv
    from wtf_tpu.interp.runner import Runner

    runner = Runner(demo_tlv.build_snapshot(), n_lanes=2, chunk_steps=32,
                    overlay_slots=8)
    view = runner.view()
    pfns = [view.translate(0, demo_tlv.INPUT_GVA) >> 12]
    payloads = [b"\x01\x04AAAA", b"\x03\x30" + b"Z" * 0x30]
    words = np.zeros((2, 1024), np.uint32)
    lens = np.zeros((2,), np.int32)
    for lane, p in enumerate(payloads):
        buf = np.zeros(4096, np.uint8)
        buf[:len(p)] = np.frombuffer(p, dtype=np.uint8)
        words[lane] = buf.view(np.uint32)
        lens[lane] = len(p)
    runner.device_insert(jnp.asarray(words), jnp.asarray(lens), pfns,
                         demo_tlv.INPUT_GVA, len_gpr=2, ptr_gpr=6)
    view = runner.view()
    for lane, p in enumerate(payloads):
        assert view.virt_read(lane, demo_tlv.INPUT_GVA, len(p)) == p
        assert view.get_reg(lane, 2) == len(p)            # rdx
        assert view.get_reg(lane, 6) == demo_tlv.INPUT_GVA  # rsi
        # padded-slab contract: bytes past len read as zero
        tail = view.virt_read(lane, demo_tlv.INPUT_GVA + len(p), 16)
        assert tail == b"\x00" * 16
    # a later host write to the inserted page updates the SAME overlay
    # row in place (no duplicate pfn rows)
    view.virt_write(0, demo_tlv.INPUT_GVA, b"\xee\xff")
    runner.push(view)
    view = runner.view()
    assert view.virt_read(0, demo_tlv.INPUT_GVA, 4) == b"\xee\xffAA"
    assert int((np.asarray(runner.machine.overlay.pfn)[0]
                == pfns[0]).sum()) == 1


def test_device_insert_preserves_pushed_host_writes():
    """run_batch_device pushes init-time host writes BEFORE the in-graph
    insert; the insert must not clobber their overlay rows (writes
    outside the input region survive) and must WIN over a pushed write
    to the input region itself (stale duplicate-pfn rows are retired —
    lookups take the first match)."""
    from wtf_tpu.harness import demo_tlv
    from wtf_tpu.interp.runner import Runner

    runner = Runner(demo_tlv.build_snapshot(), n_lanes=2, chunk_steps=32,
                    overlay_slots=8)
    view = runner.view()
    pfns = [view.translate(0, demo_tlv.INPUT_GVA) >> 12]
    # init-time host state: a write OUTSIDE the insert region and a
    # stale write INSIDE it, both pushed before the insert (the
    # run_batch_device ordering)
    view.virt_write(0, demo_tlv.SCRATCH_GVA, b"INITDATA")
    view.virt_write(0, demo_tlv.INPUT_GVA, b"STALEINPUT")
    runner.push(view)
    payload = b"\x01\x02XY"
    words = np.zeros((2, 1024), np.uint32)
    buf = np.zeros(4096, np.uint8)
    buf[:len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    words[:] = buf.view(np.uint32)
    runner.device_insert(jnp.asarray(words),
                         jnp.asarray(np.full(2, len(payload), np.int32)),
                         pfns, demo_tlv.INPUT_GVA, len_gpr=2, ptr_gpr=6)
    view = runner.view()
    # the out-of-region init write survived the insert
    assert view.virt_read(0, demo_tlv.SCRATCH_GVA, 8) == b"INITDATA"
    # the testcase won the input region (stale pushed bytes retired)
    assert view.virt_read(0, demo_tlv.INPUT_GVA, 10) == \
        payload + b"\x00" * 6
    # no duplicate live row for the input pfn on lane 0
    assert int((np.asarray(runner.machine.overlay.pfn)[0]
                == pfns[0]).sum()) == 1
    assert not np.asarray(runner.machine.overlay.overflow).any()


def _campaign(seed=0x77F, batches=2):
    from wtf_tpu.analysis.trace import build_tlv_campaign

    loop = build_tlv_campaign(n_lanes=8, mutator="devmangle",
                              limit=20_000, seed=seed, chunk_steps=128,
                              overlay_slots=16)
    for _ in range(batches):
        loop.run_one_batch()
    return loop


def test_devmangle_campaign_runs_and_is_deterministic():
    """The acceptance path: a demo_tlv devmangle campaign executes,
    finds coverage, keeps the mutate HOST share near zero (the phase is
    the nested mutate/device fence), and replays exactly under the same
    seed."""
    loop_a = _campaign(seed=0x5EED)
    assert loop_a.stats.testcases == 16
    assert loop_a.stats.new_coverage > 0
    assert len(loop_a.mutator.corpus) > 0
    spans = loop_a.registry.spans
    mutate = spans.seconds("mutate")
    mutate_dev = spans.seconds("mutate/device")
    assert mutate_dev > 0.0
    # the mutate phase is the device fence: host share is dispatch-only
    assert mutate - mutate_dev < 0.25 * mutate + 0.05
    # insert is in-graph too
    assert spans.seconds("execute/insert/device") > 0.0
    # devmut telemetry namespace is live
    assert loop_a.registry.counter("devmut.batches").value == 3  # +prelaunch
    assert loop_a.registry.counter("devmut.generated").value == 24

    loop_b = _campaign(seed=0x5EED)
    assert loop_b.stats.testcases == loop_a.stats.testcases
    assert loop_b.stats.crashes == loop_a.stats.crashes
    assert loop_b.stats.timeouts == loop_a.stats.timeouts
    assert loop_b._coverage() == loop_a._coverage()
    assert loop_b.corpus.digests == loop_a.corpus.digests


def test_devmangle_requires_device_backend_and_spec():
    import random

    from wtf_tpu.backend.emu import EmuBackend
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.loop import FuzzLoop
    from wtf_tpu.fuzz.mutator import create_mutator
    from wtf_tpu.harness import demo_tlv
    from wtf_tpu.harness.targets import Target

    mut = create_mutator("devmangle", random.Random(1), 64)
    backend = EmuBackend(demo_tlv.build_snapshot())
    backend.initialize()
    with pytest.raises(ValueError, match="tpu backend"):
        FuzzLoop(backend, demo_tlv.TARGET, mut, Corpus())
    # a target without the declarative insert spec fails with the fix
    bare = Target.__new__(Target)   # no registry side effects
    bare.name = "bare"
    bare.device_insert = None
    with pytest.raises(ValueError, match="device_insert"):
        mut.bind(backend, bare)


@pytest.mark.slow
def test_devmangle_coverage_parity_with_host_mangle():
    """Acceptance: at equal exec counts on demo_tlv, the device engine
    reaches at least the host mangle engine's edge coverage (both from
    the same single seed), and the campaign stream stays deterministic
    over a longer run."""
    from wtf_tpu.analysis.trace import build_tlv_campaign

    cov = {}
    for engine in ("mangle", "devmangle"):
        loop = build_tlv_campaign(n_lanes=8, mutator=engine, limit=20_000,
                                  seed=0xAB, chunk_steps=128,
                                  overlay_slots=16)
        for _ in range(12):
            loop.run_one_batch()
        assert loop.stats.testcases == 96
        cov[engine] = loop._coverage()
    assert cov["devmangle"] >= cov["mangle"], cov