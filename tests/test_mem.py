"""Memory subsystem tests: physmem image, dirty overlay, paging, virt I/O."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wtf_tpu.core.gxa import PAGE_SIZE
from wtf_tpu.mem.overlay import (
    overlay_init,
    overlay_reset,
    phys_read,
    phys_read_u64,
    phys_write,
)
from wtf_tpu.mem.paging import translate, virt_read, virt_read_u64, virt_write
from wtf_tpu.mem.physmem import PhysMem
from wtf_tpu.snapshot.synthetic import SyntheticSnapshotBuilder


def _lane(overlay, i=0):
    """Extract lane i's overlay view (what vmap hands the per-lane fns)."""
    return jax.tree.map(lambda x: x[i], overlay)


def _merge_lane(overlay, lane_overlay, i=0):
    return jax.tree.map(lambda full, one: full.at[i].set(one), overlay, lane_overlay)


@pytest.fixture(scope="module")
def simple_mem():
    pages = {
        3: bytes(range(256)) * 16,
        5: b"\xAA" * PAGE_SIZE,
        6: b"\xBB" * PAGE_SIZE,
    }
    return PhysMem.from_pages(pages)


def test_phys_read_base(simple_mem):
    ov = _lane(overlay_init(1, 4))
    data = phys_read(simple_mem.image, ov, jnp.uint64(3 * PAGE_SIZE + 1), 4)
    assert list(np.asarray(data)) == [1, 2, 3, 4]


def test_phys_read_absent_page_is_zero(simple_mem):
    ov = _lane(overlay_init(1, 4))
    data = phys_read(simple_mem.image, ov, jnp.uint64(0x100 * PAGE_SIZE), 8)
    assert list(np.asarray(data)) == [0] * 8
    # out of frame-table range too
    data = phys_read(simple_mem.image, ov, jnp.uint64(1 << 40), 8)
    assert list(np.asarray(data)) == [0] * 8


def test_phys_read_page_crossing(simple_mem):
    ov = _lane(overlay_init(1, 4))
    gpa = jnp.uint64(5 * PAGE_SIZE + PAGE_SIZE - 2)
    data = phys_read(simple_mem.image, ov, gpa, 4)
    assert list(np.asarray(data)) == [0xAA, 0xAA, 0xBB, 0xBB]


def test_phys_write_copy_on_write(simple_mem):
    ov = _lane(overlay_init(1, 4))
    gpa = jnp.uint64(3 * PAGE_SIZE + 10)
    ov, ok = phys_write(
        simple_mem.image, ov, gpa, jnp.array([9, 9], dtype=jnp.uint8), jnp.bool_(True)
    )
    assert bool(ok)
    assert int(ov.count) == 1
    # Readback sees the overlay; neighbors keep base content (CoW copied page).
    data = phys_read(simple_mem.image, ov, gpa - jnp.uint64(1), 4)
    assert list(np.asarray(data)) == [9, 9, 9, 12]
    # Base image untouched.
    assert simple_mem.host_read(3 * PAGE_SIZE + 10, 2) == bytes([10, 11])


def test_phys_write_disabled_is_noop(simple_mem):
    ov = _lane(overlay_init(1, 4))
    ov, _ = phys_write(
        simple_mem.image,
        ov,
        jnp.uint64(3 * PAGE_SIZE),
        jnp.array([1], dtype=jnp.uint8),
        jnp.bool_(False),
    )
    assert int(ov.count) == 0
    data = phys_read(simple_mem.image, ov, jnp.uint64(3 * PAGE_SIZE), 1)
    assert int(data[0]) == 0


def test_phys_write_crossing_and_reset(simple_mem):
    ov = _lane(overlay_init(1, 4))
    gpa = jnp.uint64(5 * PAGE_SIZE + PAGE_SIZE - 1)
    ov, ok = phys_write(
        simple_mem.image, ov, gpa, jnp.array([1, 2], dtype=jnp.uint8), jnp.bool_(True)
    )
    assert bool(ok)
    assert int(ov.count) == 2  # both pages went dirty
    data = phys_read(simple_mem.image, ov, gpa, 2)
    assert list(np.asarray(data)) == [1, 2]
    # Restore: O(1) reset drops all dirty data.
    ov = overlay_reset(ov)
    assert int(ov.count) == 0
    data = phys_read(simple_mem.image, ov, gpa, 2)
    assert list(np.asarray(data)) == [0xAA, 0xBB]


def test_overlay_overflow_flag(simple_mem):
    ov = _lane(overlay_init(1, 2))
    for pfn in (3, 5, 6):
        ov, ok = phys_write(
            simple_mem.image,
            ov,
            jnp.uint64(pfn * PAGE_SIZE),
            jnp.array([7], dtype=jnp.uint8),
            jnp.bool_(True),
        )
    assert bool(ov.overflow)
    assert not bool(ok)


def test_overlay_vmap_lanes(simple_mem):
    """Each lane's overlay is independent under vmap."""
    n = 4
    ov = overlay_init(n, 4)
    gpas = jnp.array([3 * PAGE_SIZE, 5 * PAGE_SIZE, 6 * PAGE_SIZE, 3 * PAGE_SIZE], dtype=jnp.uint64)
    vals = jnp.arange(n, dtype=jnp.uint8)[:, None]

    def write_one(ov_lane, gpa, val):
        new_ov, ok = phys_write(simple_mem.image, ov_lane, gpa, val, jnp.bool_(True))
        return new_ov, ok

    ov2, oks = jax.vmap(write_one, in_axes=(0, 0, 0))(ov, gpas, vals)
    assert bool(jnp.all(oks))

    def read_one(ov_lane, gpa):
        return phys_read(simple_mem.image, ov_lane, gpa, 1)

    out = jax.vmap(read_one, in_axes=(0, 0))(ov2, gpas)
    assert list(np.asarray(out[:, 0])) == [0, 1, 2, 3]


@pytest.fixture(scope="module")
def paged_guest():
    b = SyntheticSnapshotBuilder()
    b.write(0x140000000, b"CODEPAGE" * 512)           # 4 KiB at an exe-like GVA
    b.write(0x7FFE0000, bytes([0x11] * 32))           # another mapping
    b.map_discontiguous_pair(0x200000000)             # crossing test region
    b.write(0x200000000 + PAGE_SIZE - 4, b"ABCDEFGH", map_if_needed=False)
    pages, cpu = b.build(rip=0x140000000, rsp=0x7FFE0F00)
    return PhysMem.from_pages(pages), cpu


def test_translate_4k(paged_guest):
    mem, cpu = paged_guest
    ov = _lane(overlay_init(1, 4))
    tr = translate(mem.image, ov, jnp.uint64(cpu.cr3), jnp.uint64(0x140000123))
    assert bool(tr.ok)
    data = phys_read(mem.image, ov, tr.gpa, 5)
    assert bytes(np.asarray(data)) == (b"CODEPAGE" * 512)[0x123:0x128]


def test_translate_unmapped(paged_guest):
    mem, cpu = paged_guest
    ov = _lane(overlay_init(1, 4))
    tr = translate(mem.image, ov, jnp.uint64(cpu.cr3), jnp.uint64(0xDEADBEEF000))
    assert not bool(tr.ok)
    # non-canonical
    tr = translate(mem.image, ov, jnp.uint64(cpu.cr3), jnp.uint64(0x8000_0000_0000))
    assert not bool(tr.ok)


def test_translate_large_page():
    b = SyntheticSnapshotBuilder()
    b.write(0x1000000, b"X" * 16)  # force PML4/PDPT/PD creation nearby
    b.add_large_page_mapping(0x1200000, 0x400000, 21)  # 2 MiB page GVA->GPA
    pages, cpu = b.build()
    pages[0x400000 >> 12] = b"\xCC" * PAGE_SIZE
    mem = PhysMem.from_pages(pages)
    ov = _lane(overlay_init(1, 4))
    tr = translate(mem.image, ov, jnp.uint64(cpu.cr3), jnp.uint64(0x1200000 + 0x1234))
    assert bool(tr.ok)
    assert int(tr.gpa) == 0x400000 + 0x1234


def test_virt_read_write_roundtrip(paged_guest):
    mem, cpu = paged_guest
    ov = _lane(overlay_init(1, 8))
    cr3 = jnp.uint64(cpu.cr3)
    gva = jnp.uint64(0x7FFE0000)
    ov, fault = virt_write(
        mem.image, ov, cr3, gva, jnp.asarray(list(b"hello!"), dtype=jnp.uint8), jnp.bool_(True)
    )
    assert not bool(fault)
    data, fault = virt_read(mem.image, ov, cr3, gva, 6)
    assert not bool(fault)
    assert bytes(np.asarray(data)) == b"hello!"


def test_virt_crossing_discontiguous_phys(paged_guest):
    """Virtually contiguous pages map to non-adjacent frames; reads and
    writes must stitch the two spans correctly."""
    mem, cpu = paged_guest
    ov = _lane(overlay_init(1, 8))
    cr3 = jnp.uint64(cpu.cr3)
    gva = jnp.uint64(0x200000000 + PAGE_SIZE - 4)
    data, fault = virt_read(mem.image, ov, cr3, gva, 8)
    assert not bool(fault)
    assert bytes(np.asarray(data)) == b"ABCDEFGH"

    ov, fault = virt_write(
        mem.image, ov, cr3, gva, jnp.asarray(list(b"12345678"), dtype=jnp.uint8), jnp.bool_(True)
    )
    assert not bool(fault)
    assert int(ov.count) == 2
    data, _ = virt_read(mem.image, ov, cr3, gva, 8)
    assert bytes(np.asarray(data)) == b"12345678"


def test_virt_read_u64(paged_guest):
    mem, cpu = paged_guest
    ov = _lane(overlay_init(1, 4))
    val, fault = virt_read_u64(
        mem.image, ov, jnp.uint64(cpu.cr3), jnp.uint64(0x140000000)
    )
    assert not bool(fault)
    import struct

    assert int(val) == struct.unpack("<Q", b"CODEPAGE")[0]


def test_virt_fault_on_unmapped(paged_guest):
    mem, cpu = paged_guest
    ov = _lane(overlay_init(1, 4))
    data, fault = virt_read(
        mem.image, ov, jnp.uint64(cpu.cr3), jnp.uint64(0x666000), 4
    )
    assert bool(fault)
    ov, fault = virt_write(
        mem.image,
        ov,
        jnp.uint64(cpu.cr3),
        jnp.uint64(0x666000),
        jnp.array([1], dtype=jnp.uint8),
        jnp.bool_(True),
    )
    assert bool(fault)
    assert int(ov.count) == 0  # faulting write allocated nothing


def test_virt_write_readonly_enforcement():
    b = SyntheticSnapshotBuilder()
    b.map(0x5000000, PAGE_SIZE, writable=False)
    b.write(0x5000000, b"RO" * 8, map_if_needed=False)
    pages, cpu = b.build()
    mem = PhysMem.from_pages(pages)
    ov = _lane(overlay_init(1, 4))
    cr3 = jnp.uint64(cpu.cr3)
    vals = jnp.asarray(list(b"XX"), dtype=jnp.uint8)
    # Guest-store path faults on the read-only mapping...
    ov, fault = virt_write(mem.image, ov, cr3, jnp.uint64(0x5000000), vals,
                           jnp.bool_(True), enforce_writable=True)
    assert bool(fault)
    # ...but the host path writes through protection (reference VirtWrite
    # semantics, backend.cc:91-127).
    ov, fault = virt_write(mem.image, ov, cr3, jnp.uint64(0x5000000), vals,
                           jnp.bool_(True))
    assert not bool(fault)
    data, _ = virt_read(mem.image, ov, cr3, jnp.uint64(0x5000000), 2)
    assert bytes(np.asarray(data)) == b"XX"


def test_translate_vec_matches_host_walk(paged_guest):
    """The device's vectorized walk agrees with the independent host-side
    Python walk (runner.HostView.translate) for mapped, unmapped, and
    non-canonical addresses — the two implementations must never diverge
    (crash triage compares their results)."""
    from wtf_tpu.mem.paging import translate_vec

    mem, cpu = paged_guest
    ov = _lane(overlay_init(1, 8))
    gvas = [
        0x140000000, 0x140000123, 0x140000FFF,   # code page
        0x7FFE0000, 0x7FFE001F,                  # data page
        0x200000000 + PAGE_SIZE - 4,             # crossing pair, 1st page
        0x200000000 + PAGE_SIZE,                 # crossing pair, 2nd page
        0x1234,                                  # unmapped low
        0xDEAD00000000,                          # unmapped high
        0x8000_0000_0000,                        # non-canonical
    ]
    t = translate_vec(mem.image, ov, jnp.uint64(cpu.cr3),
                      jnp.asarray(gvas, dtype=jnp.uint64))

    # independent reference: pure-python 4-level walk over the page dict
    import wtf_tpu.interp.runner as R

    class _FakeView:
        def __init__(self):
            self.r = {"cr3": np.asarray([np.uint64(cpu.cr3)])}

        def phys_read(self, lane, gpa, size):
            out = bytearray()
            for i in range(size):
                a = gpa + i
                page = np.asarray(mem.image.pages[
                    int(mem.image.frame_table[0, a >> 12])]).tobytes()
                out.append(page[a & 0xFFF])
            return bytes(out)

    fv = _FakeView()
    for i, gva in enumerate(gvas):
        try:
            gpa = R.HostView.translate(fv, 0, gva)
            assert bool(t.ok[i]), hex(gva)
            assert int(t.gpa[i]) == gpa, hex(gva)
        except R.HostFault:
            assert not bool(t.ok[i]), hex(gva)


def test_load_windows3_vec_matches_bytes(paged_guest):
    """Batched window loads return the same bytes as the byte-granular
    compatibility path, including across a discontiguous page crossing
    and through a dirty overlay page."""
    from wtf_tpu.mem.overlay import extract_pair, load_windows3_vec
    from wtf_tpu.mem.paging import translate_vec, virt_read

    mem, cpu = paged_guest
    ov = _lane(overlay_init(1, 8))
    # dirty one page so a window reads through the overlay
    ov, fault = virt_write(mem.image, ov, jnp.uint64(cpu.cr3),
                           jnp.uint64(0x7FFE0005),
                           jnp.asarray(list(b"overlaid!"), dtype=jnp.uint8),
                           jnp.bool_(True))
    assert not bool(fault)
    starts = [0x140000000, 0x140000803,          # aligned / unaligned code
              0x7FFE0003,                        # through the dirty page
              0x200000000 + PAGE_SIZE - 4]       # discontiguous crossing
    firsts = jnp.asarray(starts, dtype=jnp.uint64)
    lasts = firsts + jnp.uint64(15)
    tf = translate_vec(mem.image, ov, jnp.uint64(cpu.cr3), firsts)
    tl = translate_vec(mem.image, ov, jnp.uint64(cpu.cr3), lasts)
    w0, w1, w2 = load_windows3_vec(mem.image, ov, tf.gpa, tl.gpa)
    lo, hi = extract_pair(w0, w1, w2, tf.gpa)
    for i, start in enumerate(starts):
        expect, fault = virt_read(mem.image, ov, jnp.uint64(cpu.cr3),
                                  jnp.uint64(start), 16)
        assert not bool(fault)
        got = int(lo[i]).to_bytes(8, "little") + int(hi[i]).to_bytes(8, "little")
        assert got == bytes(np.asarray(expect)), hex(start)
