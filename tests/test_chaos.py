"""Dist-tier fault-tolerance under the deterministic chaos harness
(wtf_tpu/testing/faultinject): reconnect with backoff, in-flight reclaim
on drop and on silence, SIGTERM drain, transient dial retry, torn corpus
tolerance — all over the real wire protocol."""

import errno
import random
import socket
import threading
import time
from pathlib import Path

import pytest

from wtf_tpu.backend import create_backend
from wtf_tpu.core.results import Ok
from wtf_tpu.dist import BatchClient, Client, MasterLink, Server, wire
from wtf_tpu.fuzz.corpus import Corpus
from wtf_tpu.fuzz.mutator import TlvStructureMutator
from wtf_tpu.harness import demo_tlv
from wtf_tpu.telemetry import Registry
from wtf_tpu.testing.faultinject import (
    FaultPlan, PARTIAL_SEND, RESET, chaos_dialing,
)

from test_harness import BENIGN, OVERFLOW, tlv


def _addr(tmp_path: Path) -> str:
    return f"unix://{tmp_path}/master.sock"


def _serve(server, seconds=120.0):
    t = threading.Thread(target=server.run, kwargs={"max_seconds": seconds})
    t.start()
    return t


def _emu_backend():
    backend = create_backend("emu", demo_tlv.build_snapshot())
    backend.initialize()
    return backend


class _Events:
    def __init__(self):
        self.records = []

    def emit(self, type, **fields):  # noqa: A002
        self.records.append({"type": type, **fields})

    def heartbeat(self, *a, **k):
        pass

    def of(self, type):  # noqa: A002
        return [r for r in self.records if r["type"] == type]


# ---------------------------------------------------------------------------
# wire: transient dial retry (satellite 1)
# ---------------------------------------------------------------------------

def test_dial_retries_transient_oserrors(tmp_path, monkeypatch):
    """EHOSTUNREACH/ETIMEDOUT/EINTR inside the retry window retry like
    ECONNREFUSED instead of aborting instantly."""
    listener = wire.listen(_addr(tmp_path))
    calls = {"n": 0}
    real = socket.socket

    class Flaky(socket.socket):
        def connect(self, addr):
            calls["n"] += 1
            if calls["n"] <= 2:
                raise OSError(
                    [errno.EHOSTUNREACH, errno.ETIMEDOUT][calls["n"] - 1],
                    "chaos")
            return real.connect(self, addr)

    monkeypatch.setattr(wire.socket, "socket", Flaky)
    try:
        sock = wire.dial(_addr(tmp_path), retry_for=10.0)
        sock.close()
    finally:
        listener.close()
    assert calls["n"] == 3  # two transient failures retried, then in


def test_dial_aborts_on_nontransient_error(tmp_path, monkeypatch):
    calls = {"n": 0}

    class Denied(socket.socket):
        def connect(self, addr):
            calls["n"] += 1
            raise PermissionError(errno.EACCES, "chaos")

    monkeypatch.setattr(wire.socket, "socket", Denied)
    with pytest.raises(PermissionError):
        wire.dial(_addr(tmp_path), retry_for=10.0)
    assert calls["n"] == 1  # configuration errors never burn the window


def test_dial_transient_reraises_past_deadline(tmp_path, monkeypatch):
    class Unreachable(socket.socket):
        def connect(self, addr):
            raise OSError(errno.EHOSTUNREACH, "chaos")

    monkeypatch.setattr(wire.socket, "socket", Unreachable)
    start = time.monotonic()
    with pytest.raises(OSError):
        wire.dial(_addr(tmp_path), retry_for=0.3)
    assert time.monotonic() - start >= 0.25


def test_tagged_wire_frames():
    a, b = socket.socketpair()
    try:
        wire.send_work(a, b"payload", tagged=True)
        wire.send_bye(a)
        assert wire.recv_tagged(b) == (wire.TAG_WORK, b"payload")
        assert wire.recv_tagged(b) == (wire.TAG_BYE, b"")
        wire.send_msg(a, b"")
        with pytest.raises(ValueError, match="empty frame"):
            wire.recv_tagged(b)
        a.close()
        assert wire.recv_tagged(b) is None
    finally:
        b.close()


def test_masterlink_bye_stops_retry(tmp_path):
    """BYE is terminal: a link with a 30s retry budget must NOT burn it
    after an orderly goodbye."""
    listener = wire.listen(_addr(tmp_path))

    def serve():
        conn, _ = listener.accept()
        wire.recv_msg(conn)  # hello
        wire.send_bye(conn)
        conn.close()

    t = threading.Thread(target=serve)
    t.start()
    link = MasterLink(_addr(tmp_path), 1, max_retry_secs=30.0,
                      rng=random.Random(0))
    link.connect()
    start = time.monotonic()
    assert link.recv_work() is None
    assert time.monotonic() - start < 5.0  # no retry loop after BYE
    assert link._bye
    link.close()
    t.join(timeout=10)
    listener.close()


def test_faultplan_seeded_is_deterministic():
    a = FaultPlan.seeded(42, n_sockets=4, faults_per_socket=2)
    b = FaultPlan.seeded(42, n_sockets=4, faults_per_socket=2)
    assert a.socket_schedules == b.socket_schedules
    assert FaultPlan.seeded(43, 4).socket_schedules != a.socket_schedules


# ---------------------------------------------------------------------------
# client reconnect + master reclaim (the chaos soak, tier-1 size)
# ---------------------------------------------------------------------------

def test_client_reconnect_chaos_zero_lost(tmp_path):
    """Scheduled resets + torn frames mid-campaign: the node reconnects
    (dist.retries), the master reclaims its in-flight work
    (dist.reclaimed), and the campaign still accounts EXACTLY
    seeds + runs results with an exactly-deduped corpus."""
    runs = 16
    rng = random.Random(7)
    outputs = tmp_path / "outputs"
    corpus = Corpus(outputs_dir=outputs, rng=rng)
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 128), corpus,
                    crashes_dir=tmp_path / "crashes", runs=runs)
    seeds = [BENIGN, tlv((2, b"ABCDEFGH"))]
    server.paths = list(seeds)
    thread = _serve(server)
    registry = Registry()
    # node op pattern: send(hello)=0, then recv,recv,send per testcase —
    # reset a result send (reclaim) and tear a later one (torn frame)
    plan = FaultPlan([{9: RESET}, {6: PARTIAL_SEND}, {}, {}],
                     delay_secs=0.002)
    with chaos_dialing(plan):
        client = Client(_emu_backend(), demo_tlv.TARGET, _addr(tmp_path),
                        registry=registry, max_retry_secs=30.0,
                        retry_rng=random.Random(1))
        served = client.run()
    thread.join(timeout=120)
    assert not thread.is_alive()
    assert plan.count_fired(RESET) == 1
    assert plan.count_fired(PARTIAL_SEND) == 1
    # zero lost: every seed and every mutation accounted exactly once
    assert server.stats.testcases == len(seeds) + runs
    assert server.mutations == runs
    assert served >= len(seeds) + runs  # re-executions land on the node
    assert registry.counter("dist.retries").value >= 2
    assert server.registry.counter("dist.reclaimed").value == 2
    # exact server-side dedup: outputs/ is content-addressed and intact
    for p in outputs.iterdir():
        from wtf_tpu.utils.hashing import hex_digest

        assert hex_digest(p.read_bytes()) == p.name


def test_mux_batch_client_reconnects(tmp_path):
    """The 1-fd batch node shape survives a mid-campaign reset too: the
    whole in-flight batch reclaims and re-serves."""
    runs = 8
    rng = random.Random(3)
    corpus = Corpus(rng=rng)
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 64), corpus,
                    crashes_dir=tmp_path / "crashes", runs=runs)
    server.paths = [BENIGN, OVERFLOW, tlv((2, b"ABCDEFGH")),
                    tlv((1, b"\x05"))]
    thread = _serve(server, seconds=180)
    backend = create_backend("tpu", demo_tlv.build_snapshot(),
                             n_lanes=4, limit=50_000)
    backend.initialize()
    registry = Registry()
    # mux node ops: send(hello)=0, recv(batch)x2, send(replies)... —
    # reset the second round's reply send: 4 in-flight testcases reclaim
    plan = FaultPlan([{6: RESET}, {}, {}], delay_secs=0.002)
    with chaos_dialing(plan):
        node = BatchClient(backend, demo_tlv.TARGET, _addr(tmp_path),
                           mux=True, registry=registry,
                           max_retry_secs=60.0,
                           retry_rng=random.Random(2))
        node.run()
    thread.join(timeout=180)
    assert not thread.is_alive()
    assert plan.count_fired(RESET) == 1
    assert server.stats.testcases == 4 + runs  # zero lost
    assert registry.counter("dist.retries").value >= 1
    assert server.registry.counter("dist.reclaimed").value >= 1
    assert server.stats.crashes >= 1  # OVERFLOW still landed


def test_client_without_retry_budget_keeps_reference_behavior(tmp_path):
    """max_retry_secs=0 (the library default): first socket loss ends
    the node, exactly the pre-fault-tolerance semantics."""
    rng = random.Random(11)
    corpus = Corpus(rng=rng)
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 64), corpus,
                    runs=50)
    server.paths = [BENIGN]
    thread = _serve(server)
    registry = Registry()
    plan = FaultPlan([{3: RESET}])  # first result send dies
    with chaos_dialing(plan):
        client = Client(_emu_backend(), demo_tlv.TARGET, _addr(tmp_path),
                        registry=registry)
        client.run(max_runs=5)
    assert registry.counter("dist.retries").value == 0
    server.runs = server.mutations  # release the master's budget wait
    thread.join(timeout=120)
    assert not thread.is_alive()


def test_wire_v1_client_speaks_legacy_hello(tmp_path):
    """`--wire-v1`: raw downstream frames against a master that predates
    WTF2 (here: the current master, which serves v1 to a v1 hello), no
    reconnect semantics — the rolling-upgrade escape hatch."""
    rng = random.Random(17)
    corpus = Corpus(rng=rng)
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 64), corpus,
                    runs=4)
    server.paths = [BENIGN]
    thread = _serve(server)
    client = Client(_emu_backend(), demo_tlv.TARGET, _addr(tmp_path),
                    max_retry_secs=30.0, wire_v1=True)
    served = client.run()
    thread.join(timeout=120)
    assert not thread.is_alive()
    assert served == 1 + 4  # full campaign over raw frames
    assert server.stats.testcases == 5


def test_batchclient_master_gone_costs_one_retry_window(tmp_path):
    """A dead master (close without BYE — what kill -9 produces) must
    cost the non-mux fleet ONE retry window, not n_lanes serial windows:
    the first exhausted lane zeroes its siblings' budgets."""
    addr = _addr(tmp_path)
    listener = wire.listen(addr)

    def accept_serve_die():
        conns = []
        for _ in range(4):
            c, _ = listener.accept()
            wire.recv_msg(c)  # hello
            conns.append(c)
        for c in conns:
            wire.send_work(c, BENIGN, tagged=True)
        time.sleep(0.3)
        for c in conns:
            c.close()
        listener.close()

    t = threading.Thread(target=accept_serve_die)
    t.start()
    backend = create_backend("tpu", demo_tlv.build_snapshot(),
                             n_lanes=4, limit=50_000)
    backend.initialize()
    node = BatchClient(backend, demo_tlv.TARGET, addr,
                       max_retry_secs=1.0, retry_rng=random.Random(3))
    start = time.monotonic()
    served = node.run()
    retry_elapsed = time.monotonic() - start
    t.join(timeout=30)
    assert served == 4  # round 1 executed; replies were abandoned
    # one ~1s window for the fleet (plus execute time), NOT 4 x 1s
    assert retry_elapsed < 3.5, retry_elapsed


# ---------------------------------------------------------------------------
# master: heartbeat-timeout reclaim + SIGTERM drain
# ---------------------------------------------------------------------------

def test_master_reclaims_silent_node(tmp_path):
    """A node that takes work and goes silent past reclaim_timeout is
    presumed dead: its in-flight testcase re-serves to a live node and
    the campaign completes with zero lost.  Seeds come from inputs/
    FILES — lazy Path entries keep the master waiting for a client even
    while no node is connected (the pre-existing minset contract), which
    makes the zombie -> reclaim -> healthy-node sequence deterministic."""
    runs = 6
    inputs = tmp_path / "inputs"
    inputs.mkdir()
    (inputs / "a").write_bytes(BENIGN)
    (inputs / "b").write_bytes(tlv((2, b"ABCDEFGH")))
    rng = random.Random(5)
    events = _Events()
    corpus = Corpus(rng=rng)
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 64), corpus,
                    inputs_dir=inputs, runs=runs, reclaim_timeout=0.3,
                    events=events)
    thread = _serve(server)
    # the zombie: greets, takes one testcase, never replies
    zombie = wire.dial(_addr(tmp_path), retry_for=10.0)
    wire.send_msg(zombie, wire.encode_hello(1))
    assert wire.recv_msg(zombie) is not None
    # wait until the master presumed it dead and reclaimed its work
    deadline = time.monotonic() + 30
    while (server.registry.counter("dist.reclaimed").value < 1
           and time.monotonic() < deadline):
        time.sleep(0.05)
    assert server.registry.counter("dist.reclaimed").value == 1
    # a healthy node now drains the whole campaign incl. the reclaim
    client = Client(_emu_backend(), demo_tlv.TARGET, _addr(tmp_path))
    served = client.run()
    thread.join(timeout=120)
    zombie.close()
    assert not thread.is_alive()
    assert server.stats.testcases == 2 + runs  # zero lost
    assert served == 2 + runs
    reclaims = events.of("reclaim")
    assert reclaims and reclaims[0]["reason"] == "timeout"


def test_sigterm_drain(tmp_path):
    """request_drain (the SIGTERM handler's body): in-flight results get
    a grace window, nodes are told BYE, coverage persists, run() exits
    with `drained` — the exit-0 path."""
    rng = random.Random(9)
    events = _Events()
    corpus = Corpus(rng=rng)
    cov_path = tmp_path / "coverage.cov"
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 64), corpus,
                    runs=10_000, coverage_path=cov_path, events=events,
                    drain_grace=2.0)
    server.paths = [BENIGN]
    thread = _serve(server)
    # a tagged node holding one in-flight testcase
    sock = wire.dial(_addr(tmp_path), retry_for=10.0)
    wire.send_msg(sock, wire.encode_hello(1, tagged=True))
    testcase = wire.recv_tagged(sock)
    assert testcase is not None and testcase[0] == wire.TAG_WORK
    server.request_drain()
    # deliver the in-flight result inside the grace window
    wire.send_msg(sock, wire.encode_result(testcase[1], {0x1000}, Ok()))
    thread.join(timeout=60)
    assert not thread.is_alive()
    assert server.drained
    # the node was told not to come back
    got = wire.recv_tagged(sock)
    assert got is not None and got[0] == wire.TAG_BYE
    sock.close()
    assert events.of("drain")
    # persisted atomically on the way out
    import json as _json

    assert _json.loads(cov_path.read_text())["addresses"] == [0x1000]


def test_cmd_master_drain_exits_zero(tmp_path, monkeypatch, capsys):
    """The CLI driver returns 0 on a drained master (the supervisor
    contract: SIGTERM -> persist -> exit 0), even with crashes on the
    books — a drain is a clean stop, not a finding."""
    import wtf_tpu.cli as cli

    def fake_run(self, max_seconds=None):
        self.stats.crashes = 3
        self.drained = True
        return self.stats

    monkeypatch.setattr(Server, "run", fake_run)
    rc = cli.main(["master", "--name", "demo_tlv",
                   "--target", str(tmp_path),
                   "--address", _addr(tmp_path),
                   "--runs", "5", "--reclaim-timeout", "30"])
    assert rc == 0
    assert "master drained" in capsys.readouterr().out


def test_sigterm_handler_installed_in_main_thread(tmp_path):
    """Server.run arms SIGTERM -> request_drain when (and only when) it
    owns the main thread, and restores the previous handler on exit."""
    import signal

    rng = random.Random(1)
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 64),
                    Corpus(rng=rng), runs=1)
    seen = {}

    def probe():
        seen["handler"] = signal.getsignal(signal.SIGTERM)
        server.request_drain()  # also ends the run() promptly

    before = signal.getsignal(signal.SIGTERM)
    orig_drain = Server._drain_step

    def drain_and_probe(self, now):
        probe()
        return orig_drain(self, now)

    server._drain_step = drain_and_probe.__get__(server)
    server.request_drain()
    server.run(max_seconds=10)  # main thread: handler installs
    assert callable(seen["handler"])
    assert seen["handler"] is not before  # the drain hook was armed
    assert signal.getsignal(signal.SIGTERM) is before  # and restored
    assert server.drained


# ---------------------------------------------------------------------------
# torn corpus replay tolerance (satellite 3)
# ---------------------------------------------------------------------------

def test_torn_corpus_file_skipped_on_replay(tmp_path):
    """A truncated/torn outputs/ entry (content no longer matches its
    digest name) is skipped with a warning + JSONL error event; the rest
    of the resume replays normally."""
    outputs = tmp_path / "outputs"
    outputs.mkdir()
    from wtf_tpu.utils.hashing import hex_digest

    good = BENIGN
    (outputs / hex_digest(good)).write_bytes(good)
    torn = tlv((2, b"ABCDEFGH"))
    # digest-named file whose content was torn by a kill mid-write
    (outputs / hex_digest(torn)).write_bytes(torn[: len(torn) // 2])
    # an operator-named inputs file is exempt from the digest contract
    inputs = tmp_path / "inputs"
    inputs.mkdir()
    (inputs / "operator-seed").write_bytes(tlv((3, b"ok")))

    rng = random.Random(13)
    events = _Events()
    corpus = Corpus(outputs_dir=outputs, rng=rng)
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 64), corpus,
                    inputs_dir=inputs, runs=0, events=events)
    thread = _serve(server)
    client = Client(_emu_backend(), demo_tlv.TARGET, _addr(tmp_path))
    served = client.run()
    thread.join(timeout=120)
    assert not thread.is_alive()
    # good output + operator seed replayed; the torn entry skipped
    assert served == 2
    assert server.stats.testcases == 2
    errs = [r for r in events.of("error")
            if r.get("kind") == "torn-corpus-file"]
    assert len(errs) == 1
