"""Assemble x86-64 snippets with the system GNU assembler for test vectors.

The reference validates emulation against bochscpu traces of real Windows
binaries (SURVEY.md §4); we don't ship binaries, so tests assemble their own
guest code with binutils `as` (Intel syntax) and run it through both
executors.  Results are cached per-snippet so repeated test runs don't
re-invoke the toolchain.
"""

from __future__ import annotations

import hashlib
import subprocess
import tempfile
from functools import lru_cache
from pathlib import Path

_CACHE_DIR = Path(tempfile.gettempdir()) / "wtf_tpu_asm_cache"


@lru_cache(maxsize=None)
def assemble(source: str) -> bytes:
    """Assemble Intel-syntax x86-64 `source` into raw machine code bytes."""
    _CACHE_DIR.mkdir(exist_ok=True)
    key = hashlib.sha256(source.encode()).hexdigest()[:24]
    cached = _CACHE_DIR / f"{key}.bin"
    if cached.exists():
        return cached.read_bytes()

    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        obj = tmp / "t.o"
        binf = tmp / "t.bin"
        proc = subprocess.run(
            ["as", "-msyntax=intel", "-mnaked-reg", "-o", str(obj), "--"],
            input=source.encode(),
            capture_output=True,
        )
        if proc.returncode != 0:
            raise RuntimeError(f"as failed:\n{proc.stderr.decode()}\nsource:\n{source}")
        subprocess.run(
            ["objcopy", "-O", "binary", "--only-section=.text", str(obj), str(binf)],
            check=True,
        )
        code = binf.read_bytes()
    cached.write_bytes(code)
    return code
