"""Multi-tenant campaign tests (wtf_tpu/tenancy).

The two contracts of the subsystem, pinned bit-exactly:

  isolation   a campaign run as a lane-subset of a heterogeneous batch
              (stacked image table, tenant-tagged decode cache,
              per-tenant prefix-credit merges) is bit-identical —
              coverage planes, corpus stream, devmut byte streams,
              crash buckets — to the same campaign run alone;
  preemption  a tenant checkpointed at a batch boundary and restored
              into a DIFFERENT placement (different tenant index and
              lane range) finishes bit-identical to an uninterrupted
              run — the placement-free remap of tenancy/state.py.

Plus scheduler mechanics (jobs.json validation, priority/round-robin
placement, preemption events), seeded lint violations for the tenancy
budget rules, and the telemetry_report tenants section.
"""

import json
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

from wtf_tpu.harness.targets import Targets, load_builtin_targets
from wtf_tpu.interp.uoptable import DecodeCache, tag_key
from wtf_tpu.tenancy.backend import TenantSpec, create_tenancy_backend
from wtf_tpu.tenancy.image import build_batch_state, stack_images
from wtf_tpu.tenancy.loop import MultiTenantLoop, TenantRuntime
from wtf_tpu.tenancy.sched import Job, Scheduler, load_jobs
from wtf_tpu.tenancy.state import (
    extract_bits, restore_tenant, save_tenant, scatter_bits,
)

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

LIMIT = 50_000
SEED_TLV = b"\x01\x04AAAA\x02\x08BBBBBBBB"
SEED_KERN = b"hello-world-123"


def _targets():
    load_builtin_targets()
    return Targets.instance()


def _build(cfg, n_lanes=None, mesh_devices=None, limit=LIMIT):
    """(backend, specs) for a tenant table of (name, target, quota)."""
    targets = _targets()
    specs = [TenantSpec(n, targets.get(t), targets.get(t).snapshot(), q)
             for n, t, q in cfg]
    n_lanes = n_lanes if n_lanes else sum(q for _, _, q in cfg)
    backend = create_tenancy_backend(specs, n_lanes, limit=limit,
                                     mesh_devices=mesh_devices)
    backend.initialize()
    for i, s in enumerate(specs):
        with backend.tenant_context(i):
            s.target.init(backend)
    return backend, specs


def _runtimes(backend, specs, cfg_mut):
    """TenantRuntimes for (name -> (mutator, seed, corpus seed))."""
    out, lane_lo = [], 0
    for i, spec in enumerate(specs):
        mut, seed, data = cfg_mut[spec.name]
        rt = TenantRuntime(spec, seed=seed, runs=1 << 20,
                           mutator_name=mut, max_len=256,
                           lane_lo=lane_lo)
        rt.corpus.add(data)
        out.append(rt)
        lane_lo += spec.lanes
    return out


def _fingerprint(backend, runtimes):
    out = {}
    for i, rt in enumerate(runtimes):
        cov, edge = backend.tenant_coverage_state(i)
        entries = backend.runner.cache.tenant_entries(i)
        out[rt.name] = {
            "local_cov": extract_bits(cov, [e[0] for e in entries]
                                      ).tobytes(),
            "edge": edge.tobytes(),
            "corpus": list(rt.corpus),
            "buckets": sorted(rt.crash_buckets),
            "rips": sorted(e[1] for e in entries),
        }
    return out


# ---------------------------------------------------------------------------
# stacked image table + tagged decode cache (host-level units)
# ---------------------------------------------------------------------------

def test_stack_images_routes_each_tenant_to_its_pages():
    targets = _targets()
    pms = [targets.get("demo_tlv").snapshot().physmem,
           targets.get("demo_kernel").snapshot().physmem]
    image = stack_images(pms)
    assert image.frame_table.shape[0] == 2
    table = np.asarray(image.frame_table)
    pages = np.asarray(image.pages)
    for t, pm in enumerate(pms):
        own = np.asarray(pm.image.frame_table)[0]
        own_pages = np.asarray(pm.image.pages)
        present = np.nonzero(own)[0]
        assert present.size, "snapshot has no mapped pages?"
        for pfn in present[:: max(1, present.size // 16)]:
            assert (pages[table[t, pfn]] == own_pages[own[pfn]]).all(), (
                f"tenant {t} pfn {pfn:#x} routed to wrong page")
        # pfns beyond this tenant's span resolve to the shared zero page
        span_t = own.shape[0]
        if span_t < table.shape[1]:
            assert (table[t, span_t:] == 0).all()


def test_decode_cache_tenant_tagged_keys():
    from wtf_tpu.cpu.decoder import decode

    cache = DecodeCache(capacity=64)
    rip = 0x1400_0000
    nop, ret = decode(b"\x90", rip), decode(b"\xc3", rip)
    i0 = cache.add(rip, nop, 5, 5, tenant=0)
    i1 = cache.add(rip, ret, 7, 7, tenant=1)
    assert i0 != i1, "two tenants at one VA must get distinct entries"
    assert cache.entry_index(rip, 0) == i0
    assert cache.entry_index(rip, 1) == i1
    assert cache.uop_at(rip, 0).raw == b"\x90"
    assert cache.uop_at(rip, 1).raw == b"\xc3"
    assert cache.rip_of(i0) == rip and cache.rip_of(i1) == rip
    # per-tenant breakpoints: arming tenant 1's does not touch tenant 0
    cache.set_breakpoint(rip, tenant=1)
    assert cache.has_breakpoint(rip, 1)
    assert not cache.has_breakpoint(rip, 0)
    assert cache.bp[i1] == 1 and cache.bp[i0] == 0
    # tenant_entries slices by tenant with global indices + real rips
    ents0 = cache.tenant_entries(0)
    ents1 = cache.tenant_entries(1)
    assert [(e[0], e[1]) for e in ents0] == [(i0, rip)]
    assert [(e[0], e[1]) for e in ents1] == [(i1, rip)]
    # checkpoint round-trip preserves tenant tags; tenant-0 entries stay
    # 4-tuples so pre-tenancy checkpoints load unchanged
    entries = cache.checkpoint_entries()
    assert len(entries[0]) == 4 and len(entries[1]) == 5
    fresh = DecodeCache(capacity=64)
    fresh.restore_entries(entries)
    assert fresh.entry_index(rip, 0) == i0
    assert fresh.entry_index(rip, 1) == i1
    assert fresh.uop_at(rip, 1).raw == b"\xc3"


def test_tag_key_is_identity_for_tenant_zero():
    assert tag_key(0x7FFF_1234) == 0x7FFF_1234
    assert tag_key(0x7FFF_1234, 3) != 0x7FFF_1234
    # untagging is the same xor
    assert tag_key(tag_key(0x7FFF_1234, 3), 3) == 0x7FFF_1234


def test_extract_scatter_bits_roundtrip():
    rng = np.random.default_rng(7)
    words = rng.integers(0, 1 << 32, size=8, dtype=np.uint64).astype(
        np.uint32)
    idxs = [3, 17, 64, 200, 255]
    local = extract_bits(words, idxs)
    back = scatter_bits(local, idxs, 8)
    for j, i in enumerate(idxs):
        want = (int(words[i >> 5]) >> (i & 31)) & 1
        assert ((int(local[j >> 5]) >> (j & 31)) & 1) == want
        assert ((int(back[i >> 5]) >> (i & 31)) & 1) == want


# ---------------------------------------------------------------------------
# isolation: mixed batch == solo, bit for bit
# ---------------------------------------------------------------------------

MUTS = {"alice": ("tlv", 42, SEED_TLV),
        "bob": ("mangle", 1337, SEED_KERN)}


def _campaign(cfg, batches=3, mesh_devices=None, capture_devmut=None,
              muts=None, limit=LIMIT):
    backend, specs = _build(cfg, mesh_devices=mesh_devices, limit=limit)
    runtimes = _runtimes(backend, specs, muts if muts else MUTS)
    loop = MultiTenantLoop(backend, runtimes, stats_every=1e9)
    for _ in range(batches):
        loop.run_one_batch()
        if capture_devmut is not None:
            for rt in runtimes:
                if rt.device:
                    words, lens = rt.mutator.current_batch()
                    capture_devmut.setdefault(rt.name, []).append(
                        (np.asarray(jax.device_get(words)).tobytes(),
                         np.asarray(jax.device_get(lens)).tobytes()))
    return backend, runtimes, _fingerprint(backend, runtimes)


def test_mixed_batch_isolation_bit_parity():
    _b1, _r1, solo_a = _campaign([("alice", "demo_tlv", 4)])
    _b2, _r2, solo_b = _campaign([("bob", "demo_kernel", 4)])
    backend, runtimes, mixed = _campaign(
        [("alice", "demo_tlv", 4), ("bob", "demo_kernel", 4)])
    # both tenants really executed their own base image
    for name in ("alice", "bob"):
        assert mixed[name]["rips"], f"{name} decoded nothing"
        assert any(b != 0 for b in mixed[name]["local_cov"])
    assert solo_a["alice"] == mixed["alice"]
    assert solo_b["bob"] == mixed["bob"]
    # the two images share VAs: the decode cache must hold them apart
    shared = set(mixed["alice"]["rips"]) & set(mixed["bob"]["rips"])
    cache = backend.runner.cache
    for rip in list(shared)[:4]:
        assert cache.entry_index(rip, 0) != cache.entry_index(rip, 1)


def test_devmangle_tenant_stream_bit_parity():
    muts = dict(MUTS, alice=("devmangle", 42, SEED_TLV))
    cap_solo: dict = {}
    cap_mix: dict = {}
    _b1, _r1, solo = _campaign([("alice", "demo_tlv", 4)],
                               capture_devmut=cap_solo, muts=muts)
    _b2, _r2, mixed = _campaign(
        [("alice", "demo_tlv", 4), ("bob", "demo_kernel", 4)],
        capture_devmut=cap_mix, muts=muts)
    # the generated byte stream itself is placement-invariant
    assert cap_solo["alice"] == cap_mix["alice"]
    assert solo["alice"] == mixed["alice"]


def test_three_tenant_mix_with_demo_pe():
    """The acceptance mix: demo_tlv + demo_kernel + demo_pe (real MSVC
    codegen) through ONE dispatch, each tenant bit-identical to its solo
    run.  Gated like test_pe_target on the census DLL."""
    import struct

    from wtf_tpu.harness import demo_pe

    if not demo_pe.available():
        pytest.skip("census DLL not present")
    benign = struct.pack("<Id", 4, 0.5) + struct.pack(
        "<12d", 1.0, 2.0, 3.0, 2.0, 3.0, 4.0, 3.0, 4.0, 5.0, 4.0, 5.0,
        6.0)
    muts = dict(MUTS, carol=("auto", 7, benign))
    cfg3 = [("alice", "demo_tlv", 4), ("bob", "demo_kernel", 4),
            ("carol", "demo_pe", 4)]
    limit = 2_000_000  # demo_pe runs real code (test_pe_target's budget)
    solos = {}
    for row in cfg3:
        _b, _r, fp = _campaign([row], batches=2, muts=muts, limit=limit)
        solos[row[0]] = fp[row[0]]
    _b, _r, mixed = _campaign(cfg3, batches=2, muts=muts, limit=limit)
    for name in ("alice", "bob", "carol"):
        assert mixed[name]["rips"], f"{name} decoded nothing"
        assert solos[name] == mixed[name], (
            f"{name} diverged between solo and the three-tenant mix")


def test_partial_plans_leave_unfilled_lanes_idle():
    backend, specs = _build([("alice", "demo_tlv", 4),
                             ("bob", "demo_kernel", 4)])
    results = backend.run_batch_tenants(
        [("host", [SEED_TLV]), ("host", [])])
    from wtf_tpu.core.results import Ok

    assert len(results) == 8
    assert all(isinstance(r, Ok) for r in results)
    # only alice's single active lane may have found coverage
    assert not any(backend.lane_found_new_coverage(lane)
                   for lane in range(4, 8))
    with pytest.raises(ValueError, match="5 testcases for 4 lanes"):
        backend.run_batch_tenants([("host", [b"x"] * 5), ("host", [])])
    with pytest.raises(ValueError, match="unknown plan kind"):
        backend.run_batch_tenants([("bogus", []), ("host", [])])


# ---------------------------------------------------------------------------
# preemption: checkpoint -> NEW placement (different tenant index/lane
# range) -> resume, bit-identical to uninterrupted
# ---------------------------------------------------------------------------

def test_preemption_resume_into_different_placement(tmp_path):
    # uninterrupted reference: alice alone for 4 batches
    _b, _r, want = _campaign([("alice", "demo_tlv", 4)], batches=4)

    # leg 1: alice alone, 2 batches, checkpoint
    backend1, specs1 = _build([("alice", "demo_tlv", 4)])
    rts1 = _runtimes(backend1, specs1, MUTS)
    rts1[0].checkpoint_dir = tmp_path / "alice"
    loop1 = MultiTenantLoop(backend1, rts1, stats_every=1e9)
    loop1.run_one_batch()
    loop1.run_one_batch()
    info = loop1.checkpoint_tenant(0)
    assert info and info["batches"] == 2

    # leg 2: alice re-placed as tenant 1 BEHIND bob (new tenant index,
    # new lane range) — the placement-free contract
    backend2, specs2 = _build([("bob", "demo_kernel", 4),
                               ("alice", "demo_tlv", 4)])
    rts2 = _runtimes(backend2, specs2, MUTS)
    rts2[1].checkpoint_dir = tmp_path / "alice"
    loop2 = MultiTenantLoop(backend2, rts2, stats_every=1e9)
    assert loop2.resume_tenant(1) == 2
    # bob idles (done-by-budget path not used; just plan him empty)
    rts2[0].runs = 0  # done => empty plan
    loop2.run_one_batch()
    loop2.run_one_batch()
    got = _fingerprint(backend2, rts2)["alice"]
    assert got == want["alice"], (
        "preempted+re-placed alice diverged from the uninterrupted run")


def test_restore_tenant_rejects_mismatched_placement(tmp_path):
    backend1, specs1 = _build([("alice", "demo_tlv", 4)])
    rts1 = _runtimes(backend1, specs1, MUTS)
    rts1[0].checkpoint_dir = tmp_path / "alice"
    loop1 = MultiTenantLoop(backend1, rts1, stats_every=1e9)
    loop1.run_one_batch()
    assert loop1.checkpoint_tenant(0)

    from wtf_tpu.resume.checkpoint import CheckpointError

    backend2, specs2 = _build([("alice", "demo_tlv", 8)])
    rt = _runtimes(backend2, specs2, MUTS)[0]
    with pytest.raises(CheckpointError, match="lanes"):
        restore_tenant(backend2, rt, 0, tmp_path / "alice")


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------

def test_load_jobs_validation(tmp_path):
    path = tmp_path / "jobs.json"
    path.write_text(json.dumps({"jobs": [
        {"name": "a", "target": "demo_tlv", "lanes": 4, "runs": 8},
        {"name": "b", "target": "demo_tlv", "lanes": 4, "runs": 8,
         "priority": 2},
    ]}))
    jobs = load_jobs(path)
    assert [j.name for j in jobs] == ["a", "b"]
    assert jobs[1].priority == 2 and jobs[0].seq == 0

    path.write_text(json.dumps([{"name": "a", "target": "t",
                                 "lanes": 4, "runs": 8, "lane": 9}]))
    with pytest.raises(ValueError, match="unknown fields"):
        load_jobs(path)
    path.write_text(json.dumps([{"name": "a", "target": "t"}]))
    with pytest.raises(ValueError, match="missing"):
        load_jobs(path)
    path.write_text(json.dumps([
        {"name": "a", "target": "t", "lanes": 4, "runs": 8},
        {"name": "a", "target": "t", "lanes": 4, "runs": 8}]))
    with pytest.raises(ValueError, match="duplicate"):
        load_jobs(path)
    with pytest.raises(ValueError, match="no placement"):
        Scheduler([Job(name="a", target="demo_tlv", lanes=64, runs=8)],
                  n_lanes=8, workdir=tmp_path)
    # names key tenant.<name>.* counters and name workdir subdirs: dots
    # would scramble the report's namespace split, separators escape
    # --workdir
    for bad in ("team.alice", "../other", "a/b", ""):
        path.write_text(json.dumps([{"name": bad, "target": "t",
                                     "lanes": 4, "runs": 8}]))
        with pytest.raises(ValueError, match="must match|missing"):
            load_jobs(path)
    with pytest.raises(ValueError, match="must match"):
        Scheduler([Job(name="x.y", target="demo_tlv", lanes=4, runs=8)],
                  n_lanes=8, workdir=tmp_path)


def test_scheduler_placement_priority_and_rotation(tmp_path):
    jobs = [Job(name="lo", target="demo_tlv", lanes=8, runs=8, seq=0),
            Job(name="hi", target="demo_tlv", lanes=8, runs=8,
                priority=1, seq=1),
            Job(name="mid", target="demo_tlv", lanes=8, runs=8, seq=2)]
    sched = Scheduler(jobs, n_lanes=8, workdir=tmp_path)
    # strict priority: hi owns the lanes until done, even after running
    assert [j.name for j in sched._place()] == ["hi"]
    jobs[1].last_round = 0
    assert [j.name for j in sched._place()] == ["hi"]
    # within a priority class, least-recently-run rotates (round-robin)
    jobs[1].done = True
    assert [j.name for j in sched._place()] == ["lo"]
    jobs[0].last_round = 1
    assert [j.name for j in sched._place()] == ["mid"]
    jobs[2].last_round = 2
    assert [j.name for j in sched._place()] == ["lo"]
    # two quota-4 jobs co-reside; a quota-8 job waits for a full budget
    small = [Job(name="x", target="demo_tlv", lanes=4, runs=8, seq=0),
             Job(name="y", target="demo_tlv", lanes=4, runs=8, seq=1),
             Job(name="z", target="demo_tlv", lanes=8, runs=8, seq=2)]
    sched2 = Scheduler(small, n_lanes=8, workdir=tmp_path)
    assert [j.name for j in sched2._place()] == ["x", "y"]


def test_scheduler_reuses_placement_across_rounds(tmp_path):
    """A solo job (nothing waiting, placement never changes) must keep
    its backend/loop live across quantum rounds — one build, no
    checkpoint-restore round trips between rounds."""
    from wtf_tpu.telemetry import Registry

    _targets()
    registry = Registry()
    jobs = [Job(name="alice", target="demo_tlv", lanes=8, runs=24,
                seed=42, mutator="tlv", max_len=256)]
    sched = Scheduler(jobs, n_lanes=8, workdir=tmp_path / "work",
                      limit=LIMIT, quantum=1, registry=registry)
    summary = sched.run()
    assert summary["alice"]["done"]
    assert sched.rounds == 3  # 24 runs / 8 lanes, 1 batch per round
    assert registry.counter("sched.builds").value == 1
    # per-round durability is kept: the quantum checkpoints still land
    assert registry.counter("tenant.alice.checkpoints").value == 3


def test_scheduler_preemption_and_report(tmp_path):
    inputs = tmp_path / "inputs"
    inputs.mkdir()
    (inputs / "seed").write_bytes(SEED_TLV)
    from wtf_tpu.telemetry import Registry, open_event_log

    _targets()
    registry = Registry()
    events = open_event_log(tmp_path / "tele")
    jobs = [Job(name="alice", target="demo_tlv", lanes=8, runs=24,
                seed=42, mutator="tlv", max_len=256, inputs=str(inputs)),
            Job(name="bob", target="demo_kernel", lanes=8, runs=16,
                seed=7, mutator="mangle", max_len=256)]
    sched = Scheduler(jobs, n_lanes=8, workdir=tmp_path / "work",
                      limit=LIMIT, quantum=1, registry=registry,
                      events=events)
    summary = sched.run()
    events.emit("run-end", metrics=registry.dump())
    events.close()
    assert summary["alice"]["done"] and summary["bob"]["done"]
    assert summary["alice"]["testcases"] == 24
    assert summary["bob"]["testcases"] == 16
    assert summary["alice"]["preemptions"] >= 1
    # final results checkpoints exist for DONE jobs too
    assert (tmp_path / "work" / "alice" / "checkpoint"
            / "checkpoint.json").exists()

    from telemetry_report import summarize

    s = summarize(tmp_path / "tele")
    ten = s["tenants"]
    assert set(ten["by_tenant"]) == {"alice", "bob"}
    assert ten["by_tenant"]["alice"]["testcases"] == 24
    assert ten["by_tenant"]["alice"]["batches"] == 3
    assert ten["sched"]["preemptions"] >= 1
    assert ten["sched"]["completions"] == 2


# ---------------------------------------------------------------------------
# mesh
# ---------------------------------------------------------------------------

def test_mesh_tenancy_bit_parity():
    if len(jax.devices()) < 8:
        pytest.skip("needs the conftest's 8 virtual devices")
    cfg = [("alice", "demo_tlv", 8), ("bob", "demo_kernel", 8)]
    _b1, _r1, single = _campaign(cfg, batches=2)
    _b2, _r2, meshed = _campaign(cfg, batches=2, mesh_devices=8)
    assert single == meshed


# ---------------------------------------------------------------------------
# lint: tenancy budget rules (seeded violations)
# ---------------------------------------------------------------------------

def test_lint_tenant_mix_instability_fires():
    from wtf_tpu.analysis.rules import check_tenant_mix_stability

    same = "module @jit  {\n  foo\n}"
    assert check_tenant_mix_stability(same, same, entry="e") == []
    findings = check_tenant_mix_stability(
        same, same.replace("foo", "bar"), entry="e")
    assert [f.rule for f in findings] == ["budget.tenant-mix"]
    assert "tenant" in findings[0].message


def test_lint_tenant_budget_drift_fires(tmp_path):
    from wtf_tpu.analysis.rules import (
        TENANT_ENTRY, load_budgets, run_tenant_rules,
    )

    budgets = load_budgets()
    doctored = dict(budgets)
    doctored[TENANT_ENTRY] = dict(budgets[TENANT_ENTRY], gather=1)
    path = tmp_path / "budgets.json"
    path.write_text(json.dumps(doctored))
    findings, info = run_tenant_rules(budgets_path=path)
    rules = {f.rule for f in findings}
    assert "budget.kernel-count" in rules, (findings, info)
    # and against the checked-in budget the family is clean
    clean, info = run_tenant_rules()
    assert clean == [], clean
    assert info["tenant_counts"]["total"] == budgets[TENANT_ENTRY]["total"]
