"""End-to-end harness tests: targets + backends + fuzz loop.

Validates VERDICT round-1 exit criteria:
  - the canonical per-testcase sequence (InsertTestcase -> Run -> Restore,
    reference client.cc:88-180) behaves identically on the emu and tpu
    backends;
  - a synthetic user-mode target's OOB write surfaces as a named crash
    end-to-end;
  - the coverage->corpus->mutate feedback loop actually guides (maze).
"""

import random

import pytest

from wtf_tpu.backend import create_backend
from wtf_tpu.core.results import Crash, Ok
from wtf_tpu.fuzz.corpus import Corpus
from wtf_tpu.fuzz.loop import FuzzLoop
from wtf_tpu.fuzz.mutator import ByteMutator, TlvStructureMutator
from wtf_tpu.harness import demo_maze, demo_tlv


def tlv(*records) -> bytes:
    out = b""
    for rtype, payload in records:
        out += bytes([rtype, len(payload)]) + payload
    return out


BENIGN = tlv((1, bytes([5, 6, 7])), (2, b"ABCDEFGH"), (3, b"ok"))
# type-3 payload long enough to smash the saved return address
OVERFLOW = tlv((3, b"A" * 64))


def make_backend(name, target_mod, **kw):
    snapshot = target_mod.build_snapshot()
    backend = create_backend(name, snapshot, **kw)
    backend.initialize()
    target_mod.TARGET.init(backend)
    return backend


@pytest.mark.parametrize("backend_name", ["emu", "tpu"])
def test_tlv_benign_and_overflow(backend_name):
    backend = make_backend(backend_name, demo_tlv, n_lanes=4) \
        if backend_name == "tpu" else make_backend(backend_name, demo_tlv)
    target = demo_tlv.TARGET

    results = backend.run_batch([BENIGN, OVERFLOW], target)
    assert isinstance(results[0], Ok), results[0]
    assert isinstance(results[1], Crash), results[1]
    assert results[1].name.startswith("crash-")
    backend.restore()

    # deterministic across a restore (the checkpoint property, SURVEY §5.4)
    results2 = backend.run_batch([BENIGN, OVERFLOW], target)
    assert type(results2[0]) is type(results[0])
    assert isinstance(results2[1], Crash)
    assert results2[1].name == results[1].name


def test_tlv_backends_agree():
    emu = make_backend("emu", demo_tlv)
    tpu = make_backend("tpu", demo_tlv, n_lanes=4)
    cases = [
        BENIGN,
        OVERFLOW,
        b"",
        b"\x01",                      # truncated header
        tlv((1, b"\xff" * 255)),      # max-len sum
        tlv((9, b"skip me"), (1, b"\x01\x02")),
        tlv((2, b"1234567")),         # type-2 below threshold
        tlv((3, b"B" * 17)),          # overflow into saved rbp only
    ]
    r_emu = emu.run_batch(cases, demo_tlv.TARGET)
    r_tpu = tpu.run_batch(cases, demo_tlv.TARGET)
    for i, (a, b) in enumerate(zip(r_emu, r_tpu)):
        assert type(a) is type(b), f"case {i}: emu={a} tpu={b}"
        if isinstance(a, Crash):
            assert a.name == b.name, f"case {i}: emu={a} tpu={b}"


def test_tlv_sum_semantics():
    """The benign path computes over guest state we can check: rbx returns
    the sum of type-1 payload bytes via rax at the stop breakpoint."""
    got = {}

    def grab_and_stop(backend):
        got["rax"] = backend.get_reg(0)
        backend.stop(Ok())

    for name in ("emu", "tpu"):
        backend = make_backend(name, demo_tlv, **(
            {"n_lanes": 2} if name == "tpu" else {}))
        backend.set_breakpoint(demo_tlv.FINISH_GVA, grab_and_stop)
        backend.run_batch([tlv((1, bytes([10, 20, 30])))], demo_tlv.TARGET)
        assert got["rax"] == 60, name


# Seeds verified to reach the maze's int3 within the run cap on each
# backend (the search is stochastic; a fixed seed makes it a deterministic
# regression test: emu finds it ~10.6k testcases, tpu-batch ~24.6k — batch
# mode pays feedback latency, 32 draws between corpus updates).
_MAZE_SEED = {"emu": 7, "tpu": 42}


@pytest.mark.parametrize("backend_name", ["emu", "tpu"])
def test_maze_guided_fuzz_finds_crash(backend_name):
    target_mod = demo_maze
    backend = make_backend(backend_name, target_mod, **(
        {"n_lanes": 32} if backend_name == "tpu" else {}))
    rng = random.Random(_MAZE_SEED[backend_name])
    corpus = Corpus(rng=rng)
    corpus.add(b"aaaa")
    mutator = ByteMutator(rng, max_len=8)
    loop = FuzzLoop(backend, target_mod.TARGET, mutator, corpus,
                    batch_size=32 if backend_name == "tpu" else 8)
    stats = loop.fuzz(runs=60_000, stop_on_crash=True)
    assert stats.crashes >= 1, (
        f"no crash after {stats.testcases} testcases "
        f"(corpus={len(corpus)})")
    # guidance evidence: intermediate stages entered the corpus
    assert len(corpus) >= 3, len(corpus)


def test_tlv_structure_mutator_shapes():
    rng = random.Random(7)
    m = TlvStructureMutator(rng, max_len=256)
    corpus = Corpus(rng=rng)
    corpus.add(BENIGN)
    for _ in range(100):
        tc = m.get_new_testcase(corpus)
        assert len(tc) <= 256
    assert m.get_new_testcase(None)  # empty-corpus generation works


def test_pe_heap_stubs_bounded_to_arena():
    """The demo_pe malloc/realloc bump stubs return NULL once an allocation
    would pass the 16-page HEAP arena (or wrap the bump pointer), instead of
    handing out pointers past the mapping — unbounded bumps made
    allocation-heavy mangled inputs crash on harness arena overruns that
    were then misattributed to the target DLL (ADVICE r5).  Runs the
    SHIPPED stub bytes on both engines (no DLL needed)."""
    from tests.test_step import assert_matches_oracle
    from wtf_tpu.harness.demo_pe import (
        HEAP_BASE, HEAP_PAGES, HEAP_STATE, _STUBS,
    )

    stub_gva = 0x2100_0000
    heap_end = HEAP_BASE + HEAP_PAGES * 0x1000
    data = {
        stub_gva: _STUBS["malloc"].ljust(0x40, b"\xcc") + _STUBS["realloc"],
        HEAP_STATE: HEAP_BASE.to_bytes(8, "little"),
        HEAP_BASE: b"\x00" * (HEAP_PAGES * 0x1000),
    }
    asm = f"""
        mov rcx, 0x20
        mov rax, {stub_gva}
        call rax
        mov r12, rax          # in-arena alloc -> HEAP_BASE
        mov byte ptr [rax], 0x5A
        mov rcx, {HEAP_PAGES * 0x1000}
        mov rax, {stub_gva}
        call rax
        mov r13, rax          # would pass HEAP_END -> NULL
        mov rcx, -32
        mov rax, {stub_gva}
        call rax
        mov r14, rax          # bump-pointer wrap -> NULL
        mov rcx, -1
        mov rax, {stub_gva}
        call rax
        mov rbp, rax          # SIZE_MAX: must not wrap through +15 align
        mov rcx, r12
        mov rdx, 0x40
        mov rax, {stub_gva + 0x40}
        call rax
        mov rbx, rax          # in-arena realloc -> next block, data copied
        movzx rbx, byte ptr [rbx]
        mov rcx, r12
        mov rdx, 0x100000
        mov rax, {stub_gva + 0x40}
        call rax
        mov r9, rax           # oversized realloc -> NULL
        mov r10, {HEAP_STATE}
        mov r15, [r10]        # final bump pointer
        hlt
    """
    _, emu = assert_matches_oracle(asm, data=data)
    assert emu.gpr[12] == HEAP_BASE                   # r12
    assert emu.gpr[13] == 0                           # r13: bounded
    assert emu.gpr[14] == 0                           # r14: wrap caught
    assert emu.gpr[5] == 0                            # rbp: SIZE_MAX -> NULL
    assert emu.gpr[3] == 0x5A                         # rbx: realloc copied
    assert emu.gpr[9] == 0                            # r9: bounded realloc
    assert emu.gpr[15] == HEAP_BASE + 0x20 + 0x40     # r15: two live blocks
    assert emu.gpr[15] < heap_end
