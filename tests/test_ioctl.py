"""demo_ioctl target tests (fuzzer_ioctl role: in-place rewrite,
page-end placement, dynamic exit breakpoint)."""

import random
import struct

import pytest

from wtf_tpu.backend import create_backend
from wtf_tpu.core.results import Crash, Ok
from wtf_tpu.fuzz.corpus import Corpus
from wtf_tpu.fuzz.loop import FuzzLoop
from wtf_tpu.fuzz.mutator import ByteMutator
from wtf_tpu.harness import demo_ioctl as di


def make_backend(name, **kw):
    backend = create_backend(name, di.build_snapshot(), limit=100_000, **kw)
    backend.initialize()
    di.TARGET.init(backend)
    return backend


def tc(code, payload=b""):
    return struct.pack("<I", code) + payload


@pytest.mark.parametrize("backend_name", ["emu", "tpu"])
def test_ioctl_classes(backend_name):
    backend = make_backend(backend_name, **(
        {"n_lanes": 4} if backend_name == "tpu" else {}))
    results = backend.run_batch([
        tc(di.IOCTL_SUM, b"\x01\x02\x03"),
        tc(di.IOCTL_PARSE, struct.pack("<H", 4) + b"ABCD"),
        tc(di.IOCTL_PARSE, struct.pack("<H", 500) + b"xx"),  # lying length
        tc(0x999, b"whatever"),
    ], di.TARGET)
    assert isinstance(results[0], Ok)
    assert isinstance(results[1], Ok)
    # OOB read faults at the page boundary thanks to page-end placement
    assert results[2].name == f"crash-read-{di.INPUT_PAGE + 0x1000:#x}"
    assert isinstance(results[3], Ok)


def test_dynamic_exit_breakpoint():
    """init() discovers the stop address from the saved return address,
    not from a symbol (the snapshot ships no exit symbol at all)."""
    snap = di.build_snapshot()
    assert "ioctl!exit" not in snap.symbols
    backend = make_backend("emu")
    assert di.EXIT_GVA in backend.breakpoints


def test_ioctl_fuzz_finds_oob():
    backend = make_backend("emu")
    rng = random.Random(4)
    corpus = Corpus(rng=rng)
    corpus.add(tc(di.IOCTL_PARSE, struct.pack("<H", 2) + b"AB"))
    loop = FuzzLoop(backend, di.TARGET, ByteMutator(rng, 64), corpus)
    stats = loop.fuzz(runs=30_000, stop_on_crash=True)
    assert stats.crashes >= 1, stats.testcases
    assert any("crash-read-" in n for n in loop.crash_names)
