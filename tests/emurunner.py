"""Shared helper: build a synthetic guest around an assembled snippet and run
it on the Python oracle CPU (and later the TPU machine) until `hlt`."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from tests.asmhelper import assemble
from wtf_tpu.cpu.emu import EmuCpu, EmuMem, GuestCrash
from wtf_tpu.mem.physmem import PhysMem
from wtf_tpu.snapshot.synthetic import SyntheticSnapshotBuilder

CODE_BASE = 0x0001_4000_1000
DATA_BASE = 0x0002_0000_0000
STACK_TOP = 0x0000_7FFF_F000


def build_guest(asm: str, data: Optional[Dict[int, bytes]] = None):
    """Assemble `asm` at CODE_BASE with a stack and optional data mappings.
    Returns (PhysMem, CpuState, code bytes)."""
    code = assemble(asm)
    b = SyntheticSnapshotBuilder()
    b.write(CODE_BASE, code)
    b.map(STACK_TOP - 0x4000, 0x5000)
    if data:
        for gva, blob in data.items():
            b.write(gva, blob)
    pages, cpu = b.build(rip=CODE_BASE, rsp=STACK_TOP - 0x100)
    return PhysMem.from_pages(pages), cpu, code


def run_emu(asm: str, data: Optional[Dict[int, bytes]] = None,
            max_steps: int = 100_000, regs: Optional[Dict[str, int]] = None) -> EmuCpu:
    """Run until hlt (the canonical snippet terminator) or `max_steps`."""
    physmem, cpustate, _ = build_guest(asm, data)
    if regs:
        for name, value in regs.items():
            setattr(cpustate, name, value)
    cpu = EmuCpu(EmuMem(physmem), cpustate)
    for _ in range(max_steps):
        try:
            cpu.step()
        except GuestCrash:
            return cpu
    raise AssertionError(f"snippet did not hlt within {max_steps} steps (rip={cpu.rip:#x})")
