// Differential fixture for tests/test_kdmp.py (VERDICT r3 item 4): parse a
// crash dump with the REFERENCE kdmp-parser (compiled from its header-only
// sources via -I at test time; nothing of it is vendored here) and print
// what it saw as one JSON line.  The test compares this against
// wtf_tpu/snapshot/kdmp.py's native and pure-Python parsers — breaking the
// closed writer->parser loop: a shared misreading of the format between our
// writer and our parser cannot also fool the battle-tested upstream parser.
//
// Build (test-time): g++ -O1 -std=c++20 -I <ref>/src/libs/kdmp-parser/src/lib
//                    kdmp_ref_check.cc -o kdmp_ref_check
#include "kdmp-parser.h"

#include <algorithm>
#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <vector>

static uint64_t fnv1a(const uint8_t *data, size_t len, uint64_t h) {
  for (size_t i = 0; i < len; i++) {
    h ^= data[i];
    h *= 0x100000001b3ULL;
  }
  return h;
}

int main(int argc, const char *argv[]) {
  if (argc != 2) {
    fprintf(stderr, "usage: kdmp_ref_check <dump>\n");
    return 2;
  }
  kdmpparser::KernelDumpParser dmp;
  if (!dmp.Parse(argv[1])) {
    fprintf(stderr, "reference parser rejected the dump\n");
    return 1;
  }
  const kdmpparser::CONTEXT *c = dmp.GetContext();
  const auto &physmem = dmp.GetPhysmem();
  std::vector<uint64_t> pas;
  pas.reserve(physmem.size());
  for (const auto &[pa, _] : physmem) {
    pas.push_back(pa);
  }
  std::sort(pas.begin(), pas.end());
  // one digest over (pa, content) in ascending-pa order: page-set AND
  // byte-content differences both change it
  uint64_t digest = 0xcbf29ce484222325ULL;
  for (const uint64_t pa : pas) {
    digest = fnv1a(reinterpret_cast<const uint8_t *>(&pa), 8, digest);
    digest = fnv1a(physmem.at(pa), 0x1000, digest);
  }
  printf("{\"type\": %u, \"dtb\": %" PRIu64 ", \"n_pages\": %zu, "
         "\"rip\": %" PRIu64 ", \"rsp\": %" PRIu64 ", \"rax\": %" PRIu64 ", "
         "\"rcx\": %" PRIu64 ", \"r15\": %" PRIu64 ", \"eflags\": %u, "
         "\"seg_cs\": %u, \"seg_ss\": %u, "
         "\"first_pa\": %" PRIu64 ", \"last_pa\": %" PRIu64 ", "
         "\"pages_digest\": %" PRIu64 "}\n",
         static_cast<uint32_t>(dmp.GetDumpType()),
         dmp.GetDirectoryTableBase(), physmem.size(), c->Rip, c->Rsp, c->Rax,
         c->Rcx, c->R15, c->EFlags, c->SegCs, c->SegSs,
         pas.empty() ? 0 : pas.front(), pas.empty() ? 0 : pas.back(), digest);
  return 0;
}
