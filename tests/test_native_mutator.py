"""Native mutation engine tests (SURVEY §2.6: mutator engines are
compiled code in the reference; wtf_tpu/native/mangle.cc is ours)."""

import random

import pytest

from wtf_tpu.fuzz.corpus import Corpus
from wtf_tpu.fuzz import native_mutator
from wtf_tpu.fuzz.native_mutator import (
    NativeMangleMutator, best_mangle_mutator, native_available,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no native toolchain")


def _corpus(rng, *seeds):
    corpus = Corpus(rng=rng)
    for seed in seeds:
        corpus.add(seed)
    return corpus


def test_mutates_and_bounds():
    rng = random.Random(7)
    m = NativeMangleMutator(rng, max_len=64)
    corpus = _corpus(rng, b"\x01\x04AAAA\x02\x08BBBBBBBB")
    changed = 0
    for _ in range(200):
        tc = m.get_new_testcase(corpus)
        assert 1 <= len(tc) <= 64
        if tc != b"\x01\x04AAAA\x02\x08BBBBBBBB":
            changed += 1
    assert changed > 150  # overwhelmingly actually mutates


def test_deterministic_for_seed():
    def run(seed):
        rng = random.Random(seed)
        m = NativeMangleMutator(rng, max_len=32)
        corpus = _corpus(rng, b"hello world!")
        return [m.get_new_testcase(corpus) for _ in range(50)]

    assert run(123) == run(123)
    assert run(123) != run(124)


def test_empty_corpus_generates():
    rng = random.Random(1)
    m = NativeMangleMutator(rng, max_len=32)
    tc = m.get_new_testcase(None)
    assert 1 <= len(tc) <= 64


def test_cross_over_spreads_coverage_seed():
    rng = random.Random(3)
    m = NativeMangleMutator(rng, max_len=32)
    m.on_new_coverage(b"MAGICMARKER")
    corpus = _corpus(rng, b"\x00" * 32)
    hits = sum(b"MAGIC" in m.get_new_testcase(corpus) for _ in range(300))
    assert hits > 0  # the splice op fires ~1/11 of mutations


def test_batch_api_matches_constraints():
    rng = random.Random(9)
    m = NativeMangleMutator(rng, max_len=48)
    corpus = _corpus(rng, b"base-testcase-bytes", b"\x01\x02\x03")
    batch = m.get_new_batch(corpus, 64)
    assert len(batch) == 64
    assert all(1 <= len(tc) <= 48 for tc in batch)
    assert len(set(batch)) > 30  # diverse, not copies of one mutation


def test_batch_drives_fuzz_loop():
    """FuzzLoop prefers the one-native-call batch path and still finds
    the demo_tlv crash."""
    from wtf_tpu.backend import create_backend
    from wtf_tpu.fuzz.loop import FuzzLoop
    from wtf_tpu.harness import demo_tlv

    backend = create_backend("emu", demo_tlv.build_snapshot(), limit=50_000)
    backend.initialize()
    demo_tlv.TARGET.init(backend)
    rng = random.Random(3)  # seed verified: crash at ~4k testcases
    corpus = _corpus(rng, b"\x03\x08CCCCCCCC")
    loop = FuzzLoop(backend, demo_tlv.TARGET,
                    NativeMangleMutator(rng, 128), corpus, batch_size=16)
    stats = loop.fuzz(runs=20_000, stop_on_crash=True)
    assert stats.crashes >= 1, stats.testcases


def test_best_mutator_selects_native():
    rng = random.Random(0)
    assert isinstance(best_mangle_mutator(rng, 32), NativeMangleMutator)
