"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip hardware isn't available in CI; sharded paths are validated on a
virtual CPU mesh (jax's xla_force_host_platform_device_count), matching the
driver's dryrun_multichip environment.

Robustness note: some environments pre-register a TPU PJRT plugin from a
sitecustomize hook and export JAX_PLATFORMS=<plugin> — in that case jax is
already imported before this conftest runs and mutating os.environ alone is
too late.  jax.config.update("jax_platforms", ...) still wins as long as no
backend has been initialized, so we set both.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
