"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip hardware isn't available in CI; sharded paths are validated on a
virtual CPU mesh (jax's xla_force_host_platform_device_count), matching the
driver's dryrun_multichip environment.  Must run before jax is imported.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
