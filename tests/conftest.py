"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip hardware isn't available in CI; sharded paths are validated on a
virtual CPU mesh (jax's xla_force_host_platform_device_count), matching the
driver's dryrun_multichip environment.

Robustness note: some environments pre-register a TPU PJRT plugin from a
sitecustomize hook and export JAX_PLATFORMS=<plugin> — in that case jax is
already imported before this conftest runs and mutating os.environ alone is
too late.  jax.config.update("jax_platforms", ...) still wins as long as no
backend has been initialized, so we set both.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the interpreter step function is large
# (~40s per XLA compile on a 1-core box) and tests compile it for several
# (lanes, chunk) shapes; caching across test processes cuts reruns from
# ~10 min to ~2 min.
jax.config.update("jax_compilation_cache_dir",
                  os.path.expanduser("~/.cache/wtf_tpu_xla"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
