"""Worker process for the 2-process jax.distributed test (VERDICT r3
item 6; PR 7 re-pointed it at the meshrun subsystem).  Launched by
tests/test_parallel.py with env:

  WTF_COORD   coordinator address (localhost:port)
  WTF_NPROC   number of processes
  WTF_PID     this process's id
  (JAX_PLATFORMS=cpu and xla_force_host_platform_device_count are set by
  the parent so each process contributes 4 virtual CPU devices)

Joins the distributed runtime via init_multihost, runs one shard_map
mesh chunk (wtf_tpu/meshrun/executor.py) over the global 8-device mesh —
the same executor MeshRunner dispatches — and reads back the merged
coverage bitmap its in-graph boolean all-reduce produced (DCN-analog
collective).  Prints one JSON line whose coverage digest the parent
compares across both processes.
"""

import json
import os
import sys


def main() -> None:
    import numpy as np

    from wtf_tpu.harness import demo_tlv
    from wtf_tpu.interp.runner import Runner, warm_decode_cache
    from wtf_tpu.meshrun import (
        init_multihost, make_mesh_chunk, replicate, shard_machine,
    )

    mesh = init_multihost(coordinator=os.environ["WTF_COORD"],
                          num_processes=int(os.environ["WTF_NPROC"]),
                          process_id=int(os.environ["WTF_PID"]))
    import jax
    import jax.numpy as jnp

    n_devices = len(jax.devices())
    assert n_devices == mesh.size, (n_devices, mesh.size)

    payload = b"\x01\x02AB\x03\x08CCCCCCCC"
    n_lanes = 2 * n_devices
    snapshot = demo_tlv.build_snapshot()
    runner = Runner(snapshot, n_lanes=n_lanes, uop_capacity=1 << 10,
                    overlay_slots=8, edge_bits=12, chunk_steps=8)
    warm_decode_cache(runner, demo_tlv.TARGET, payload, limit=4096)
    view = runner.view()
    for lane in range(n_lanes):
        view.virt_write(lane, demo_tlv.INPUT_GVA, payload)
        view.r["gpr"][lane, 2] = np.uint64(len(payload))
    runner.push(view)

    machine = shard_machine(runner.machine, mesh)
    tab = replicate(runner.cache.device(), mesh)
    image = replicate(runner.physmem.image, mesh)
    # the mesh chunk's in-graph coverage all-reduce IS the cross-process
    # collective under test: its output is replicated on every host
    machine, cov, _edge = make_mesh_chunk(8, mesh, donate=False)(
        tab, image, machine, jnp.uint64(500))

    from jax.experimental import multihost_utils

    cov_local = np.asarray(cov.addressable_shards[0].data)
    icount = np.asarray(
        multihost_utils.process_allgather(machine.icount, tiled=True))
    assert icount.shape[0] == n_lanes, icount.shape
    print(json.dumps({
        "pid": int(os.environ["WTF_PID"]),
        "devices": n_devices,
        "lanes": n_lanes,
        "instructions": int(icount.sum()),
        "min_lane_icount": int(icount.min()),
        "cov_words_set": int((cov_local != 0).sum()),
        "cov_digest": hex(int(np.bitwise_xor.reduce(
            cov_local.astype(np.uint64) * np.arange(1, len(cov_local) + 1,
                                                    dtype=np.uint64)))),
    }))


if __name__ == "__main__":
    sys.exit(main())
