"""Unified telemetry subsystem tests (wtf_tpu/telemetry/ + the device
counter block): registry counter/label semantics, span fencing, JSONL
schema round-trip, device-counter vs oracle differentials, campaign
wall-clock accounting, and the report tool."""

import json
import sys
from pathlib import Path

import numpy as np
import pytest

from wtf_tpu.backend import create_backend
from wtf_tpu.backend.emu import EmuBackend
from wtf_tpu.core.results import Crash, Ok
from wtf_tpu.dist.client import run_testcase_and_restore
from wtf_tpu.harness import demo_tlv
from wtf_tpu.interp.machine import (
    CTR_DECODE_MISS, CTR_INSTR, CTR_MEM_FAULT, N_CTRS,
)
from wtf_tpu.telemetry import (
    EventLog, NULL, Registry, StatsDict, get_registry, open_event_log,
    read_events,
)

from test_harness import BENIGN, OVERFLOW

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_semantics():
    reg = Registry()
    c = reg.counter("x.count")
    c.inc()
    c.inc(5)
    assert c.value == 6
    assert reg.counter("x.count") is c  # idempotent registration
    g = reg.gauge("x.depth")
    g.set(3)
    g.set(2)
    assert g.value == 2
    h = reg.histogram("x.lat")
    for v in (0.5, 1.5, 1.0):
        h.observe(v)
    d = h.dump()
    assert d == {"count": 3, "sum": 3.0, "min": 0.5, "max": 1.5}
    with pytest.raises(TypeError):
        reg.gauge("x.count")  # type mismatch on re-registration


def test_labeled_children_semantics():
    reg = Registry()
    c = reg.counter("fallbacks")
    c.labels("ssefp").inc(3)
    c.labels("x87").inc()
    assert c.labels("ssefp").value == 3
    assert reg.dump()["fallbacks"] == {"ssefp": 3, "x87": 1}


def test_registry_dump_is_json_able():
    reg = Registry()
    reg.counter("a").inc()
    reg.counter("b").labels("k").inc()
    reg.histogram("h").observe(1)
    reg.gauge("g").set(7)
    parsed = json.loads(json.dumps(reg.dump()))
    assert parsed["a"] == 1 and parsed["g"] == 7
    assert parsed["b"] == {"k": 1}


def test_stats_dict_facade_preserves_dict_api():
    reg = Registry()
    stats = StatsDict(reg, "runner", fields=("chunks", "fallbacks"),
                      gauges=("max_chunk_steps",),
                      labeled=("fallbacks_by_opclass",))
    stats["chunks"] += 1
    stats["chunks"] += 1
    stats["max_chunk_steps"] = max(stats["max_chunk_steps"], 512)
    by_class = stats["fallbacks_by_opclass"]
    by_class["ssefp"] = by_class.get("ssefp", 0) + 1
    assert stats["chunks"] == 2
    assert stats["max_chunk_steps"] == 512
    assert dict(stats["fallbacks_by_opclass"]) == {"ssefp": 1}
    assert set(stats) >= {"chunks", "fallbacks", "max_chunk_steps"}
    # the same numbers are visible registry-side (the whole point)
    dump = reg.dump()
    assert dump["runner.chunks"] == 2
    assert dump["runner.fallbacks_by_opclass"] == {"ssefp": 1}
    # a declared-labeled key with no children dumps as {} (not 0)
    stats2 = StatsDict(Registry(), "r", labeled=("by_x",))
    assert stats2._registry.dump()["r.by_x"] == {}


def test_registry_isolation_between_instances():
    a, b = Registry(), Registry()
    a.counter("n").inc()
    assert b.counter("n").value == 0
    assert get_registry() is get_registry()  # global singleton exists


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def test_span_records_monotonic_and_nested_paths():
    reg = Registry()
    clock = [0.0]

    def fake_clock():
        return clock[0]

    from wtf_tpu.telemetry.spans import Spans

    spans = Spans(reg, clock=fake_clock)
    with spans.span("execute"):
        clock[0] += 1.0
        with spans.span("device-step"):
            clock[0] += 2.0
        clock[0] += 0.5
    with spans.span("restore"):
        clock[0] += 0.25
    secs = reg.counter("phase.seconds").children
    assert secs["execute"].value == pytest.approx(3.5)
    assert secs["execute/device-step"].value == pytest.approx(2.0)
    assert secs["restore"].value == pytest.approx(0.25)
    calls = reg.counter("phase.calls").children
    assert calls["execute"].value == 1
    assert spans.seconds("execute") == pytest.approx(3.5)
    # re-entry accumulates and stays monotonic
    with spans.span("execute"):
        clock[0] += 1.0
    assert secs["execute"].value == pytest.approx(4.5)


def test_span_records_on_exception_and_rebalances_stack():
    reg = Registry()
    spans = reg.spans
    with pytest.raises(ValueError):
        with spans.span("boom"):
            raise ValueError("x")
    assert reg.counter("phase.calls").children["boom"].value == 1
    # the stack recovered: a new span is top-level, not nested under boom
    with spans.span("after"):
        pass
    assert "after" in reg.counter("phase.seconds").children


def test_span_fence_blocks_device_values():
    import jax.numpy as jnp

    reg = Registry()
    with reg.spans.span("device") as sp:
        value = jnp.arange(8).sum()
        sp.fence(value)  # must not raise; host values fine too
        sp.fence(None)
        sp.fence({"nested": [value]})
    assert reg.spans.seconds("device") >= 0.0


# ---------------------------------------------------------------------------
# JSONL event log
# ---------------------------------------------------------------------------

def test_event_log_schema_round_trip(tmp_path):
    reg = Registry()
    reg.counter("campaign.testcases").inc(7)
    path = tmp_path / "telem"
    with open_event_log(path) as log:
        log.emit("run-start", subcommand="test", argv=["--x"])
        log.heartbeat(reg, line="#7 exec/s: 1.0", nodes=2)
        log.emit("crash", name="crash-read-0xdead", size=9)
        log.emit("run-end", metrics=reg.dump())
    records = list(read_events(path / "events.jsonl"))
    assert [r["type"] for r in records] == [
        "run-start", "heartbeat", "crash", "run-end"]
    # schema: every record has ts + monotonically increasing seq
    assert all("ts" in r for r in records)
    assert [r["seq"] for r in records] == [0, 1, 2, 3]
    hb = records[1]
    assert hb["line"] == "#7 exec/s: 1.0" and hb["nodes"] == 2
    assert hb["metrics"]["campaign.testcases"] == 7
    assert records[3]["metrics"]["campaign.testcases"] == 7
    # append mode: a second log continues the file
    with EventLog(path / "events.jsonl") as log:
        log.emit("run-start")
    assert len(list(read_events(path / "events.jsonl"))) == 5


def test_event_log_skips_torn_tail(tmp_path):
    path = tmp_path / "events.jsonl"
    with EventLog(path) as log:
        log.emit("run-start")
    with open(path, "a") as fh:
        fh.write('{"ts": 1.0, "seq": 1, "type": "hea')  # killed mid-write
    records = list(read_events(path))
    assert len(records) == 1


def test_event_log_rotates_at_size_cap(tmp_path):
    """Size-based rotation: events.jsonl -> events.jsonl.1, exactly one
    generation of history, and read_events(rotated=True) replays both
    generations oldest-first."""
    path = tmp_path / "events.jsonl"
    with EventLog(path, max_bytes=400) as log:
        for i in range(40):
            log.emit("tick", n=i)
    rotated = tmp_path / "events.jsonl.1"
    assert rotated.exists()
    assert not (tmp_path / "events.jsonl.2").exists()  # one gen only
    assert rotated.stat().st_size >= 400  # rotation fired AT the cap
    current = [r["n"] for r in read_events(path)]
    merged = [r["n"] for r in read_events(path, rotated=True)]
    assert len(current) < 40  # the cap actually bounded the live file
    # both generations parse, in order, ending at the newest record;
    # older rotated-away generations are the deliberate loss
    assert merged == list(range(merged[0], 40))
    assert merged[:len(merged) - len(current)] + current == merged


def test_event_log_torn_tail_survives_rotation(tmp_path):
    """A killed run can freeze a torn line into the generation that then
    rotates to .1 — readers must skip it in EVERY generation (the
    test_event_log_skips_torn_tail contract, extended to rotation)."""
    path = tmp_path / "events.jsonl"
    with EventLog(path, max_bytes=150) as log:
        log.emit("run-start")
        log._fh.write('{"ts": 1.0, "seq": 99, "type": "hea')  # torn
        log._fh.flush()
        # this record glues onto the torn tail (one unparseable line)
        # and its size pushes the file past the cap -> rotation
        log.emit("casualty", fill="x" * 200)
        log.emit("after-rotation")
    assert (tmp_path / "events.jsonl.1").exists()
    types = [r["type"] for r in read_events(path, rotated=True)]
    assert types == ["run-start", "after-rotation"]


def test_null_event_log_swallows_everything(tmp_path):
    assert open_event_log(None) is NULL
    NULL.emit("crash", name="x")
    NULL.heartbeat(Registry(), line="y")
    NULL.flush()
    NULL.close()


def test_event_log_degrades_to_noop_on_write_failure(tmp_path):
    """Telemetry is a side-channel: a full disk must not abort the
    campaign it narrates — emit degrades to a no-op after one OSError."""
    path = tmp_path / "events.jsonl"
    log = EventLog(path)
    log.emit("run-start")

    class _BrokenFH:
        closed = False

        def write(self, s):
            raise OSError(28, "No space left on device")

        def flush(self):
            pass

        def close(self):
            pass

    log._fh = _BrokenFH()
    log.emit("heartbeat")  # must not raise
    assert log._broken
    log.emit("crash", name="x")  # silent no-op now
    log.flush()
    log.close()
    assert [r["type"] for r in read_events(path)] == ["run-start"]


def test_maybe_heartbeat_skips_line_fn_when_unobserved():
    """line_fn can cost a device coverage readback — it must not run when
    neither a human (print_stats) nor a real event sink consumes it."""
    from wtf_tpu.fuzz.loop import CampaignStats

    stats = CampaignStats(Registry())
    calls = []

    def line_fn():
        calls.append(1)
        return "#0 line"

    assert stats.maybe_heartbeat(NULL, None, line_fn, every=0.0) is None
    assert not calls
    assert stats.maybe_heartbeat(NULL, None, line_fn, every=0.0,
                                 print_stats=True) == "#0 line"
    assert len(calls) == 1


# ---------------------------------------------------------------------------
# trace timeline (--trace-out)
# ---------------------------------------------------------------------------

def test_trace_collector_round_trip(tmp_path):
    """Spans mirrored into a TraceCollector -> Chrome-trace JSON: exact
    event count, µs durations, device/host categorization by fenced
    leaf, child spans nested inside their parents, instants carried."""
    from wtf_tpu.telemetry.spans import Spans, TraceCollector

    reg = Registry()
    clock = [100.0]  # non-zero epoch: ts must rebase to the first event
    collector = TraceCollector(clock=lambda: clock[0])
    spans = Spans(reg, clock=lambda: clock[0])
    spans.collector = collector
    with spans.span("execute"):
        clock[0] += 1.0
        with spans.span("device-step"):
            clock[0] += 2.0
    collector.instant("compile", {"chunk_steps": 64})
    with spans.span("harvest"):
        clock[0] += 0.5

    n = collector.write(tmp_path / "trace.json")
    doc = json.loads((tmp_path / "trace.json").read_text())
    events = doc["traceEvents"]
    assert n == len(events) == 4
    assert doc["displayTimeUnit"] == "ms"
    assert doc["otherData"]["dropped_events"] == 0
    by_name = {e["name"]: e for e in events}
    dev = by_name["device-step"]
    assert dev["ph"] == "X" and dev["cat"] == "device"
    assert dev["dur"] == pytest.approx(2e6)  # µs
    assert dev["args"]["path"] == "execute/device-step"
    exe = by_name["execute"]
    assert exe["cat"] == "host" and exe["dur"] == pytest.approx(3e6)
    assert exe["ts"] == 0.0  # rebased epoch
    # nesting: the child interval lies inside the parent interval
    assert exe["ts"] <= dev["ts"]
    assert dev["ts"] + dev["dur"] <= exe["ts"] + exe["dur"]
    inst = by_name["compile"]
    assert inst["ph"] == "i" and inst["cat"] == "event"
    assert inst["args"]["chunk_steps"] == 64
    # the registry totals are untouched by mirroring
    assert reg.counter("phase.seconds").children["execute"].value == \
        pytest.approx(3.0)


def test_trace_collector_bounds_memory_by_dropping_oldest():
    from wtf_tpu.telemetry.spans import TraceCollector

    clock = [0.0]
    collector = TraceCollector(clock=lambda: clock[0], max_events=10)
    for i in range(25):
        clock[0] += 1.0
        collector.complete(f"p{i}", clock[0], 0.1)
    events = collector.trace_events()
    assert len(events) <= 10
    assert collector.dropped == 25 - len(events)
    # the survivors are the NEWEST events (steady state, not startup)
    assert {e["name"] for e in events} <= {f"p{i}" for i in range(15, 25)}


# ---------------------------------------------------------------------------
# device-side counters
# ---------------------------------------------------------------------------

def _tpu_backend(n_lanes=2):
    backend = create_backend("tpu", demo_tlv.build_snapshot(),
                             n_lanes=n_lanes, limit=100_000,
                             chunk_steps=128)
    backend.initialize()
    demo_tlv.TARGET.init(backend)
    return backend


@pytest.fixture(scope="module")
def tpu_backend():
    return _tpu_backend()


def test_device_instr_counter_matches_oracle_differential(tpu_backend):
    """The instructions-retired counter must equal the oracle
    interpreter's icount for the same testcase on both backends — the
    anchor that makes every derived rate trustworthy."""
    emu = EmuBackend(demo_tlv.build_snapshot(), limit=100_000)
    emu.initialize()
    demo_tlv.TARGET.init(emu)
    result, _ = run_testcase_and_restore(emu, demo_tlv.TARGET, BENIGN)
    assert isinstance(result, Ok)
    oracle_instr = emu.stats["instructions"]
    assert oracle_instr > 0

    backend = tpu_backend
    backend.restore()
    results = backend.run_batch([BENIGN, BENIGN], demo_tlv.TARGET)
    assert all(isinstance(r, Ok) for r in results)
    ctr = backend.runner.device_counters()
    assert ctr.shape == (2, N_CTRS)
    icount = np.asarray(backend.runner.machine.icount)
    for lane in range(2):
        assert int(ctr[lane, CTR_INSTR]) == int(icount[lane]) == oracle_instr
    # folded host metrics carry the batch totals
    assert (backend.registry.counter("device.instructions").value
            >= 2 * oracle_instr)
    backend.restore()


def test_compile_event_fires_for_base_chunk_size(tmp_path):
    """The coldest XLA compile of a campaign (the base chunk size's first
    dispatch) must be reported — make_run_chunk pre-builds the callable
    at init, but jit compiles on the first CALL.  Uses an executor shape
    (chunk size) no other test dispatches: compile tracking is
    process-global like the jit cache, so a warm shape rightly stays
    silent."""
    with EventLog(tmp_path / "events.jsonl") as events:
        backend = create_backend("tpu", demo_tlv.build_snapshot(),
                                 n_lanes=2, limit=100_000,
                                 chunk_steps=96, events=events)
        backend.initialize()
        demo_tlv.TARGET.init(backend)
        backend.run_batch([BENIGN], demo_tlv.TARGET)
        backend.restore()
        backend.run_batch([BENIGN], demo_tlv.TARGET)
    compiles = [r for r in read_events(tmp_path / "events.jsonl")
                if r["type"] == "compile"]
    # exactly one event for the base size: fired on the FIRST dispatch
    # (cold compile), silent on the warm second batch
    assert len([r for r in compiles if r["chunk_steps"] == 96]) == 1, compiles


def test_device_decode_miss_counter_and_restore_reset():
    backend = _tpu_backend()  # fresh: cold decode cache
    backend.run_batch([BENIGN], demo_tlv.TARGET)
    ctr = backend.runner.device_counters()
    assert int(ctr[0, CTR_DECODE_MISS]) > 0  # cold cache missed at least once
    assert backend.registry.counter("device.decode_misses").value > 0
    backend.restore()
    assert int(backend.runner.device_counters().sum()) == 0  # reset wipes
    # warm cache: a re-run misses nothing
    backend.run_batch([BENIGN], demo_tlv.TARGET)
    assert int(backend.runner.device_counters()[0, CTR_DECODE_MISS]) == 0


def test_device_mem_fault_counter_on_memory_crash(tpu_backend):
    backend = tpu_backend
    backend.restore()
    results = backend.run_batch([OVERFLOW], demo_tlv.TARGET)
    assert isinstance(results[0], Crash)
    ctr = backend.runner.device_counters()
    if any(kind in (results[0].name or "")
           for kind in ("read", "write", "execute")):
        assert int(ctr[0, CTR_MEM_FAULT]) >= 1
    assert backend.registry.counter("device.mem_faults").value >= int(
        ctr[0, CTR_MEM_FAULT])
    backend.restore()


# ---------------------------------------------------------------------------
# campaign integration: spans account for wall-clock, events flow
# ---------------------------------------------------------------------------

def test_campaign_telemetry_accounts_wall_clock(tmp_path):
    """Acceptance criterion: a fuzz run with --telemetry-dir produces a
    JSONL whose top-level per-phase span totals account for >= 90% of the
    run's wall-clock (run-start -> run-end)."""
    from wtf_tpu.cli import main

    telem = tmp_path / "telem"
    rc = main(["campaign", "--name", "demo_tlv", "--backend", "emu",
               "--runs", "200", "--seed", "7", "--max_len", "64",
               "--crashes", str(tmp_path / "crashes"),
               "--telemetry-dir", str(telem)])
    assert rc in (0, 2)
    records = list(read_events(telem / "events.jsonl"))
    assert records[0]["type"] == "run-start"
    end = [r for r in records if r["type"] == "run-end"]
    assert end, [r["type"] for r in records]
    metrics = end[-1]["metrics"]
    wall = end[-1]["ts"] - records[0]["ts"]
    top = {name: secs
           for name, secs in metrics["phase.seconds"].items()
           if "/" not in name}
    assert wall > 0
    assert sum(top.values()) >= 0.9 * wall, (top, wall)
    # phases tile the batch loop
    assert {"mutate", "execute", "harvest", "restore"} <= set(top)
    assert metrics["campaign.testcases"] >= 200


def test_campaign_crash_and_heartbeat_events(tmp_path):
    from wtf_tpu.cli import main

    telem = tmp_path / "telem"
    rc = main(["campaign", "--name", "demo_tlv", "--backend", "emu",
               "--runs", "600", "--seed", "5", "--max_len", "128",
               "--crashes", str(tmp_path / "crashes"),
               "--stop-on-crash", "--telemetry-dir", str(telem)])
    assert rc == 2
    types = [r["type"] for r in read_events(telem / "events.jsonl")]
    assert "crash" in types
    assert "heartbeat" in types  # last_print starts at 0 -> first batch
    assert types[-1] == "run-end"


def test_run_end_written_when_setup_fails(tmp_path):
    """A failed backend build must still close the JSONL with a run-end
    record — a telemetry file that just stops is indistinguishable from a
    killed run."""
    from wtf_tpu.cli import main

    telem = tmp_path / "telem"
    (tmp_path / "state").mkdir()  # exists but holds no snapshot
    with pytest.raises((Exception, SystemExit)):
        main(["campaign", "--name", "demo_tlv", "--backend", "emu",
              "--runs", "1", "--state", str(tmp_path / "state"),
              "--telemetry-dir", str(telem)])
    records = list(read_events(telem / "events.jsonl"))
    assert records[0]["type"] == "run-start"
    assert records[-1]["type"] == "run-end"


def test_fuzz_loop_stats_attribute_api_still_works():
    """CampaignStats keeps the reference-shaped attribute API while the
    values live in the registry."""
    from wtf_tpu.fuzz.loop import CampaignStats

    reg = Registry()
    stats = CampaignStats(reg)
    stats.testcases += 3
    stats.crashes += 1
    assert stats.testcases == 3
    assert reg.dump()["campaign.testcases"] == 3
    line = stats.line(5, 17)
    assert line.startswith("#3 cov: 17 corp: 5 exec/s: ")
    assert "crash: 1" in line
    # the node-shaped line omits cov/corp but keeps the rest
    assert stats.line().startswith("#3 exec/s: ")


# ---------------------------------------------------------------------------
# trace writers: context-manager + flush (satellite)
# ---------------------------------------------------------------------------

def test_trace_writers_context_manager_and_flush(tmp_path):
    from wtf_tpu.trace import (
        CovTraceWriter, RipTraceWriter, TenetTraceWriter,
    )

    rip_path = tmp_path / "rip.txt"
    with RipTraceWriter(rip_path) as w:
        w.on_step(0x1000)
        w.flush()  # buffered lines reach disk BEFORE close
        assert rip_path.read_text() == "0x1000\n"
        w.on_step(0x1001)
    assert rip_path.read_text() == "0x1000\n0x1001\n"
    w.close()  # double-close is safe
    with CovTraceWriter(tmp_path / "cov.txt") as w:
        w.on_step(0x2000)
        w.on_step(0x2000)
    assert (tmp_path / "cov.txt").read_text() == "0x2000\n"
    regs = {name: 0 for name in
            ("rax", "rbx", "rcx", "rdx", "rbp", "rsp", "rsi", "rdi", "r8",
             "r9", "r10", "r11", "r12", "r13", "r14", "r15", "rip")}
    try:
        with TenetTraceWriter(tmp_path / "tenet.txt") as w:
            w.on_step(regs)
            raise RuntimeError("crash mid-trace")
    except RuntimeError:
        pass
    # the crashed run's buffered lines were not lost
    assert "rax=0x0" in (tmp_path / "tenet.txt").read_text()


# ---------------------------------------------------------------------------
# report tool smoke test
# ---------------------------------------------------------------------------

def test_telemetry_report_on_bench_output(tmp_path, capsys, monkeypatch):
    """The CI/tooling satellite end-to-end: bench.py --telemetry writes a
    registry-derived JSON + an events.jsonl, and telemetry_report
    summarizes that bench output (per-phase share, testcases/s)."""
    import bench
    import telemetry_report

    monkeypatch.setenv("BENCH_SECONDS", "1")
    monkeypatch.setenv("BENCH_TELEM_LANES", "2")
    monkeypatch.setenv("BENCH_TELEM_CHUNK", "128")
    telem = tmp_path / "telem"
    bench.telemetry_mode(str(telem))
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # the bench JSON is DERIVED from the registry dump
    assert report["metrics"]["campaign.testcases"] >= 2
    assert "execute" in report["phases"]
    summary = telemetry_report.summarize(telem)
    assert summary["testcases"] == report["metrics"]["campaign.testcases"]
    assert summary["phases"]  # per-phase time share present
    assert summary["device"]["instructions"] > 0


def test_telemetry_report_segments_appended_runs(tmp_path):
    """EventLog appends, so one events.jsonl can hold several runs; the
    report must summarize the LATEST run, not stretch wall-clock across
    the gap between runs (which would crater every rate and share)."""
    import telemetry_report

    path = tmp_path / "events.jsonl"
    reg = Registry()
    reg.counter("campaign.testcases").inc(100)
    reg.counter("phase.seconds").labels("execute").inc(9.0)
    clock = iter([0.0, 1.0,            # run 1: start, end
                  3600.0, 3610.0])     # run 2, an hour later: 10s long
    with EventLog(path, clock=lambda: next(clock)) as log:
        log.emit("run-start")
        log.emit("run-end", metrics={})
        log.emit("run-start")
        log.emit("run-end", metrics=reg.dump())
    summary = telemetry_report.summarize(path)
    assert summary["runs_in_file"] == 2
    assert summary["wall_seconds"] == 10.0  # NOT 3610
    assert summary["testcases_per_s"] == 10.0
    assert summary["phase_accounted_frac"] == 0.9


def test_telemetry_report_summarizes_campaign(tmp_path, capsys):
    from wtf_tpu.cli import main

    import telemetry_report

    telem = tmp_path / "telem"
    rc = main(["campaign", "--name", "demo_tlv", "--backend", "emu",
               "--runs", "150", "--seed", "9", "--max_len", "64",
               "--telemetry-dir", str(telem)])
    assert rc in (0, 2)
    summary = telemetry_report.summarize(telem)
    assert summary["testcases"] >= 150
    assert summary["phase_accounted_frac"] >= 0.9
    assert summary["wall_seconds"] > 0
    assert "execute" in summary["phases"]
    assert summary["events_by_type"]["run-start"] == 1
    # CLI entry: --json emits one parseable object, human mode prints
    assert telemetry_report.main([str(telem), "--json"]) == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(out)["testcases"] == summary["testcases"]
    assert telemetry_report.main([str(telem)]) == 0
    assert "phases" in capsys.readouterr().out
    assert telemetry_report.main([]) == 1


def test_telemetry_report_host_device_wall_breakdown(tmp_path):
    """ISSUE 6 satellite: the report splits each top-level phase into
    host-busy vs device-busy wall-clock from the fenced nested spans —
    the artifact that makes the devmut double-buffer overlap claim
    directly measurable.  With mutate-on-device, mutate's host share is
    its total minus the nested mutate/device fence."""
    import telemetry_report

    path = tmp_path / "events.jsonl"
    reg = Registry()
    sec = reg.counter("phase.seconds")
    sec.labels("mutate").inc(2.0)
    sec.labels("mutate/device").inc(1.9)          # fenced generation wait
    sec.labels("execute").inc(10.0)
    sec.labels("execute/device-step").inc(7.0)
    sec.labels("execute/insert/device").inc(1.0)  # fused insert wait
    sec.labels("execute/service-pull").inc(2.0)   # host servicing
    sec.labels("harvest").inc(0.5)
    with EventLog(path) as log:
        log.emit("run-start")
        log.emit("run-end", metrics=reg.dump())
    wb = telemetry_report.summarize(path)["wall_breakdown"]
    assert wb["by_phase"]["mutate"]["device_seconds"] == 1.9
    assert round(wb["by_phase"]["mutate"]["host_seconds"], 4) == 0.1
    assert wb["by_phase"]["execute"]["device_seconds"] == 8.0
    assert wb["by_phase"]["execute"]["host_seconds"] == 2.0
    assert wb["by_phase"]["harvest"]["device_seconds"] == 0.0
    assert round(wb["host_busy_seconds"], 4) == 2.6
    assert round(wb["device_busy_seconds"], 4) == 9.9
