"""Multi-chip sharding tests on the conftest's 8 virtual CPU devices.

Proves the properties dryrun_multichip relies on but (deliberately, for
compile-budget reasons) no longer re-checks:
  - sharded lane-axis execution is bit-identical to single-device execution
  - merged_coverage equals the host-side union of per-lane bitmaps
  - a full fuzz batch drives identically through a sharded machine
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wtf_tpu.harness import demo_tlv
from wtf_tpu.interp.runner import Runner, warm_decode_cache
from wtf_tpu.interp.step import make_run_chunk
from wtf_tpu.meshrun.mesh import make_mesh, replicate, shard_machine
from wtf_tpu.meshrun.reduce import merged_coverage

PAYLOAD = b"\x01\x02AB\x03\x08CCCCCCCC"
N_DEVICES = 8
N_LANES = 16


def _runner() -> Runner:
    snapshot = demo_tlv.build_snapshot()
    runner = Runner(snapshot, n_lanes=N_LANES, uop_capacity=1 << 10,
                    overlay_slots=16, edge_bits=12, chunk_steps=8)
    warm_decode_cache(runner, demo_tlv.TARGET, PAYLOAD, limit=4096)
    view = runner.view()
    for lane in range(N_LANES):
        # vary per-lane input length so lanes diverge
        data = PAYLOAD[:4 + (lane % 3) * 5]
        view.virt_write(lane, demo_tlv.INPUT_GVA, data)
        view.r["gpr"][lane, 2] = np.uint64(len(data))
    runner.push(view)
    return runner


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N_DEVICES, "conftest should provision 8"
    return make_mesh(N_DEVICES)


def test_sharded_chunk_bit_parity(mesh):
    """run_chunk over a sharded machine == run_chunk single-device, for
    every machine leaf (not just coverage)."""
    r1 = _runner()
    run_chunk = make_run_chunk(8)
    m_single = run_chunk(r1.cache.device(), r1.physmem.image,
                         r1.machine, jnp.uint64(500))

    r2 = _runner()
    machine = shard_machine(r2.machine, mesh)
    tab = replicate(r2.cache.device(), mesh)
    image = replicate(r2.physmem.image, mesh)
    with mesh:
        m_sharded = run_chunk(tab, image, machine, jnp.uint64(500))

    for name in m_single._fields:
        a, b = getattr(m_single, name), getattr(m_sharded, name)
        for leaf_a, leaf_b in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(
                np.asarray(leaf_a), np.asarray(leaf_b),
                err_msg=f"machine leaf {name} diverges under sharding")


def test_merged_coverage_matches_host_union(mesh):
    r = _runner()
    run_chunk = make_run_chunk(8)
    machine = shard_machine(r.machine, mesh)
    tab = replicate(r.cache.device(), mesh)
    image = replicate(r.physmem.image, mesh)
    with mesh:
        machine = run_chunk(tab, image, machine, jnp.uint64(500))
        cov, edge = merged_coverage(machine)
    cov_host = np.bitwise_or.reduce(np.asarray(machine.cov), axis=0)
    edge_host = np.bitwise_or.reduce(np.asarray(machine.edge), axis=0)
    np.testing.assert_array_equal(np.asarray(cov), cov_host)
    np.testing.assert_array_equal(np.asarray(edge), edge_host)
    assert cov_host.sum() > 0  # something actually executed


def test_sharded_full_run_statuses(mesh):
    """Drive the full Runner loop (host servicing included) with the
    machine sharded over the mesh; statuses must match the unsharded run."""
    r1 = _runner()
    from wtf_tpu.core.results import Ok

    # plant the finish breakpoint like the target does
    r1.cache.set_breakpoint(demo_tlv.FINISH_GVA)
    statuses1 = r1.run(bp_handler=_stop_handler)

    r2 = _runner()
    r2.cache.set_breakpoint(demo_tlv.FINISH_GVA)
    r2.machine = shard_machine(r2.machine, mesh)
    with mesh:
        statuses2 = r2.run(bp_handler=_stop_handler)
    np.testing.assert_array_equal(statuses1, statuses2)


def _stop_handler(runner, view, lane):
    from wtf_tpu.core.results import StatusCode

    view.set_status(lane, StatusCode.OK)


def test_merged_coverage_groups_hint(mesh):
    """Passing groups = mesh.size (the wide-mesh escape hatch) produces
    the same union as the default grouping."""
    r = _runner()
    run_chunk = make_run_chunk(8)
    machine = shard_machine(r.machine, mesh)
    tab = replicate(r.cache.device(), mesh)
    image = replicate(r.physmem.image, mesh)
    with mesh:
        machine = run_chunk(tab, image, machine, jnp.uint64(500))
        cov_default, edge_default = merged_coverage(machine)
        cov_hint, edge_hint = merged_coverage(machine, groups=mesh.size)
    np.testing.assert_array_equal(np.asarray(cov_hint),
                                  np.asarray(cov_default))
    np.testing.assert_array_equal(np.asarray(edge_hint),
                                  np.asarray(edge_default))


def test_two_process_distributed_mesh(tmp_path):
    """VERDICT r3 item 6: 2 jax.distributed processes (coordinator on
    localhost, 4+4 virtual CPU devices) run a sharded chunk and a
    cross-process coverage OR-reduce through init_multihost.  Both
    processes must see the same global coverage; skipped when the
    distributed runtime cannot spawn (sandboxed CI)."""
    import json
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(worker)))
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            WTF_COORD=f"127.0.0.1:{port}",
            WTF_NPROC="2",
            WTF_PID=str(pid),
            PYTHONPATH=f"{repo}:" + os.environ.get("PYTHONPATH", ""),
        )
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.skip("distributed runtime hung in this environment")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        if rc != 0 and ("DISTRIBUTED" in err.upper()
                        or "grpc" in err.lower()
                        or "coordination" in err.lower()
                        # this jaxlib's CPU client cannot EXECUTE
                        # multiprocess programs (it can compile them;
                        # the single-process 8-device tests still cover
                        # the sharded path) — a real pod runtime can
                        or "multiprocess computations" in err.lower()):
            pytest.skip(f"distributed runtime unavailable: {err[-200:]}")
        assert rc == 0, err[-2000:]
    reports = [json.loads(next(ln for ln in out.splitlines()
                               if ln.startswith("{")))
               for _, out, _ in outs]
    assert reports[0]["devices"] == reports[1]["devices"] == 8
    assert reports[0]["min_lane_icount"] > 0
    assert reports[0]["cov_words_set"] > 0
    # the cross-process OR-reduce must agree bit-for-bit on every host
    assert reports[0]["cov_digest"] == reports[1]["cov_digest"]
    assert reports[0]["instructions"] == reports[1]["instructions"]
