"""demo_pe: real Windows machine code end to end (VERDICT r4 item 3).

These tests execute REAL MSVC codegen — `gle64.vc14.dll` out of PyOpenGL's
wheel, the same census-verified image the README decode table measures —
through both backends: loader-style image mapping, synthetic import
stubs, an actual exported function, and a genuine attacker-controlled
OOB read that faults off the end of the testcase buffer.
"""

import struct

import numpy as np
import pytest

from wtf_tpu.backend import create_backend
from wtf_tpu.core.results import Crash, Ok
from wtf_tpu.harness import demo_pe

pytestmark = pytest.mark.skipif(
    not demo_pe.available(), reason="census DLL not present")

BENIGN_PTS = struct.pack(
    "<12d", 1.0, 2.0, 3.0, 2.0, 3.0, 4.0, 3.0, 4.0, 5.0, 4.0, 5.0, 6.0)
BENIGN = struct.pack("<Id", 4, 0.5) + BENIGN_PTS
OVERCLAIM = struct.pack("<Id", 100_000, 0.5) + BENIGN_PTS


def make_backend(name, **kw):
    backend = create_backend(name, demo_pe.build_snapshot(),
                             limit=2_000_000, **kw)
    backend.initialize()
    demo_pe.TARGET.init(backend)
    return backend


def test_real_dll_executes_on_oracle():
    be = make_backend("emu")
    demo_pe.TARGET.insert_testcase(be, BENIGN)
    result = be.run()
    assert isinstance(result, Ok)
    assert be.cpu.icount > 5000        # thousands of real MSVC instructions
    be.restore()
    demo_pe.TARGET.insert_testcase(be, OVERCLAIM)
    crash = be.run()
    assert isinstance(crash, Crash)
    assert crash.name and "read" in crash.name


def test_real_dll_crash_name_equality_across_backends():
    """The canonical cross-backend check (reference README.md:241-243's
    develop-on-bochs/validate-on-kvm workflow): identical results and
    crash names from the oracle and the device on real code."""
    results = {}
    for backend_name in ("emu", "tpu"):
        kw = {"n_lanes": 2} if backend_name == "tpu" else {}
        be = make_backend(backend_name, **kw)
        out = []
        for tc in (BENIGN, OVERCLAIM, struct.pack("<Id", 0, 1.0)):
            demo_pe.TARGET.insert_testcase(be, tc)
            out.append(be.run())
            be.restore()
        results[backend_name] = out
    for r_emu, r_tpu in zip(results["emu"], results["tpu"]):
        assert type(r_emu) is type(r_tpu), (r_emu, r_tpu)
        if isinstance(r_emu, Crash):
            assert r_emu.name == r_tpu.name


def test_real_dll_device_fp_stays_on_device():
    """gle64's SSE2 floating point must ride the device fast path: the
    round-4 regression was every FP instruction bouncing to the oracle."""
    be = make_backend("tpu", n_lanes=2)
    demo_pe.TARGET.insert_testcase(be, BENIGN)
    result = be.run()
    assert isinstance(result, Ok)
    assert int(np.asarray(be.runner.machine.icount).max()) > 5000
    # a handful of fallbacks are legitimate (none expected today); what
    # must NOT happen is per-FP-instruction bouncing (thousands)
    assert be.runner.stats["fallbacks"] < 50, be.runner.stats


def test_real_dll_batch_campaign():
    """A small coverage-guided batch on the device backend: mixed clean
    and crashing inputs resolve per lane."""
    be = make_backend("tpu", n_lanes=4)
    results = be.run_batch(
        [BENIGN, OVERCLAIM, struct.pack("<Id", 3, 2.0) + BENIGN_PTS[:72],
         BENIGN], demo_pe.TARGET)
    assert isinstance(results[0], Ok)
    assert isinstance(results[1], Crash)
    assert isinstance(results[3], Ok)
    assert results[1].name == "crash-read-0x24002000"


def test_pe_custom_mutator_campaign_finds_the_oob():
    """The structure-aware mutator's count lies walk real MSVC code off
    the points buffer within a few batches (the custom-mutator posture
    the reference demos on tlv_server, exercised on a real DLL)."""
    import random

    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.loop import FuzzLoop

    rng = random.Random(7)
    be = make_backend("tpu", n_lanes=8)
    corpus = Corpus(rng=rng)
    corpus.add(BENIGN)
    mutator = demo_pe.TARGET.create_mutator(rng, 0x400)
    loop = FuzzLoop(be, demo_pe.TARGET, mutator, corpus)
    for _ in range(8):
        loop.run_one_batch()
        if loop.stats.crashes:
            break
    assert loop.stats.crashes > 0
    assert any(n.startswith("crash-read-") for n in loop.crash_names), (
        loop.crash_names)


def test_pe_loader_exports_and_image():
    from wtf_tpu.utils.pe import load_pe

    pe = load_pe(demo_pe.DEFAULT_DLL)
    exports = pe.exports()
    assert exports["glePolyCylinder"] > 0
    assert len(exports) == 25
    img = pe.mapped_image()
    assert img[:2] == b"MZ"
    text = pe.section(".text")
    assert img[text.vaddr:text.vaddr + 16] == pe.section_bytes(".text")[:16]
