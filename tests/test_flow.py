"""Shared dataflow engine + contract families (wtf_tpu/analysis/flow.py,
wtf_tpu/analysis/contracts.py).

Two layers, mirroring test_analysis.py:

  * negative paths: every contract-family violation class is SEEDED —
    an uncheckpointed mutable attribute, a hidden `.item()` inside a
    doctored dispatch seam, a transfer-census drift, an unlocked
    cross-thread write, a stale/undocumented contracts.json row — and
    must fire its NAMED rule with file:line provenance;
  * clean paths: the engine primitives against the real tree, the
    contracts.json ratchet semantics, and (slow tier) the full
    `--deep` contract pass clean with the census matching the
    budgets.json `host_transfer` pin.
"""

import ast
import importlib
import textwrap

import pytest

from wtf_tpu.analysis import contracts as CT
from wtf_tpu.analysis import flow
from wtf_tpu.analysis.findings import Finding, to_sarif
from wtf_tpu.analysis.rules import (
    check_supervised_seams, check_telemetry_seams, load_budgets, run_lint,
)


def _tmp_module(tmp_path, monkeypatch, name, src):
    """Materialize an importable throwaway module.  Names must be unique
    per test: flow's AST caches key on the module name."""
    (tmp_path / f"{name}.py").write_text(textwrap.dedent(src))
    monkeypatch.syspath_prepend(str(tmp_path))
    importlib.invalidate_caches()
    return name


# ---------------------------------------------------------------------------
# engine primitives
# ---------------------------------------------------------------------------

def test_resolve_site_real_tree():
    info = flow.resolve_site("wtf_tpu.interp.runner:Runner.run")
    assert info.qualname == "Runner.run"
    assert info.file.endswith("runner.py")
    assert info.lineno > 0
    assert isinstance(info.node, ast.FunctionDef)


def test_resolve_site_unresolvable_raises():
    with pytest.raises(Exception):
        flow.resolve_site("wtf_tpu.interp.runner:Runner.no_such_method")
    with pytest.raises(Exception):
        flow.resolve_site("wtf_tpu.no_such_module:X.y")


def test_attribute_writes_cover_compound_targets():
    node = ast.parse(textwrap.dedent("""
        def f(self, xs):
            self.a = 1
            self.b, self.c = 1, 2
            self.d += 1
            for self.e in xs:
                pass
            with open("x") as self.g:
                pass
    """)).body[0]
    attrs = {a for a, _ in flow.attribute_writes(node, "self")}
    assert attrs == {"a", "b", "c", "d", "e", "g"}


def test_attribute_writes_nested_scope_flag():
    node = ast.parse(textwrap.dedent("""
        def f(self):
            self.outer = 1
            def inner():
                self.inner_attr = 2
    """)).body[0]
    flat = {a for a, _ in flow.attribute_writes(node, "self",
                                                include_nested=False)}
    deep = {a for a, _ in flow.attribute_writes(node, "self")}
    assert flat == {"outer"}
    assert deep == {"outer", "inner_attr"}


def test_call_classifiers():
    node = ast.parse(textwrap.dedent("""
        def f(self, x):
            self.supervisor.dispatch("chunk", x)
            y = x.item()
            z = float(x)
            k = bool(True)          # constant arg: not a coercion
            w = np.asarray(x)
            g = jax.device_get(x)
            payload = json.dumps({})
            snap = self.registry.snapshot()
    """)).body[0]
    assert flow.dispatch_seams(node) == {"chunk"}
    coercions = {k for k, _ in flow.coercion_calls(node)}
    assert coercions == {".item()", "float()", "np.asarray()",
                         "jax.device_get()"}
    serial = {k for k, _ in flow.serialization_calls(node)}
    assert serial == {"json.dumps(", ".snapshot("}


def test_resolve_transitive_matches_parity_resolver():
    src = textwrap.dedent("""
        base = {U.OPC_ADD}
        extra = {U.OPC_SUB}
        hot = base | extra
        hot |= {U.OPC_XOR}
    """)

    def opc(node):
        return {s.attr for s in ast.walk(node)
                if isinstance(s, ast.Attribute)
                and isinstance(s.value, ast.Name) and s.value.id == "U"}

    assert flow.resolve_transitive(src, "hot", opc) == \
        {"OPC_ADD", "OPC_SUB", "OPC_XOR"}
    with pytest.raises(ValueError, match="no `cold = ...` assignment"):
        flow.resolve_transitive(src, "cold", opc)


def test_thread_root_closure_excludes_other_roots(tmp_path, monkeypatch):
    mod = _tmp_module(tmp_path, monkeypatch, "flowmod_roots", """
        class Srv:
            def run(self):
                self._helper()
                self.stop()          # calls ANOTHER root's entry: not
                                     # absorbed into this root's closure
            def _helper(self):
                self.polled = self.flag
            def stop(self):
                self.flag = True
    """)
    acc = flow.thread_root_accesses(mod, "Srv",
                                    {"reactor": ["run"],
                                     "control": ["stop"]})
    assert "flag" in acc["reactor"]["reads"]      # via _helper
    assert "flag" not in acc["reactor"]["writes"]  # stop() stayed out
    assert "flag" in acc["control"]["writes"]


# ---------------------------------------------------------------------------
# seeded violations: state family
# ---------------------------------------------------------------------------

def test_state_uncheckpointed_fires_with_provenance(tmp_path, monkeypatch):
    mod = _tmp_module(tmp_path, monkeypatch, "flowmod_state", """
        class Camp:
            def __init__(self):
                self.a = 0
            def step(self):
                self.cursor = 1
            def checkpoint_state(self):
                return {"a": self.a}
    """)
    surface = {f"{mod}:Camp": [(mod, "Camp.checkpoint_state", "self")]}
    findings = CT.check_state_contracts({"state": {}}, surface=surface)
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "state.uncheckpointed"
    assert f.primitive == "cursor"
    assert f.file.endswith("flowmod_state.py")
    assert f.line == 6  # the `self.cursor = 1` write
    assert "flowmod_state.py:6" in str(f)
    # a declared disposition clears it; a junk kind does not
    declared = {"state": {f"{mod}:Camp": {
        "cursor": {"kind": "transient", "reason": "per-step"}}}}
    assert CT.check_state_contracts(declared, surface=surface) == []
    junk = {"state": {f"{mod}:Camp": {
        "cursor": {"kind": "whatever", "reason": "x"}}}}
    assert len(CT.check_state_contracts(junk, surface=surface)) == 1


def test_state_extractor_coverage_counts_both_directions(
        tmp_path, monkeypatch):
    """restore_state WRITES through the param; that is coverage too."""
    mod = _tmp_module(tmp_path, monkeypatch, "flowmod_state2", """
        class Camp:
            def bump(self):
                self.n = 1
            @staticmethod
            def restore_state(camp, blob):
                camp.n = blob["n"]
    """)
    surface = {f"{mod}:Camp": [(mod, "Camp.restore_state", "camp")]}
    assert CT.check_state_contracts({"state": {}}, surface=surface) == []


# ---------------------------------------------------------------------------
# seeded violations: transfer family
# ---------------------------------------------------------------------------

def test_hidden_item_in_doctored_seam_fires(tmp_path, monkeypatch):
    mod = _tmp_module(tmp_path, monkeypatch, "flowmod_seam", """
        def seam(x):
            return x.item()
    """)
    findings = CT.check_transfer_seams({"transfer": {}},
                                       sites={"s": f"{mod}:seam"})
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "transfer.hidden-sync"
    assert f.primitive == ".item()"
    assert f.count == 1 and f.budget == 0
    assert f.file.endswith("flowmod_seam.py") and f.line == 3
    # an allowlist row with a matching count absorbs it
    allowed = {"transfer": {f"{mod}:seam": [
        {"call": ".item()", "count": 1, "reason": "doc'd harvest"}]}}
    assert CT.check_transfer_seams(allowed,
                                   sites={"s": f"{mod}:seam"}) == []


def test_transfer_census_drift_fires():
    measured = {"megachunk_window_fused": 9, "devmut_generate": 2,
                "device_insert": 0, "decode_service": 0, "total": 11}
    budget = load_budgets()["host_transfer"]
    findings = CT.check_transfer_census(measured, budget)
    rules = {(f.rule, f.primitive) for f in findings}
    assert ("transfer.census-drift", "megachunk_window_fused") in rules
    assert ("transfer.census-drift", "total") in rules
    assert len(findings) == 2  # the in-budget programs stay silent
    assert all(f.file == "budgets.json" for f in findings)
    # at or under the pin: clean
    ok = {k: v for k, v in budget.items() if k != "entry"}
    assert CT.check_transfer_census(ok, budget) == []


# ---------------------------------------------------------------------------
# seeded violations: thread family
# ---------------------------------------------------------------------------

def test_unlocked_shared_write_fires(tmp_path, monkeypatch):
    mod = _tmp_module(tmp_path, monkeypatch, "flowmod_thread", """
        class Srv:
            def run(self):
                while not self._stop:
                    pass
            def stop(self):
                self._stop = True
    """)
    surface = {f"{mod}:Srv": {"reactor": ("run",), "control": ("stop",)}}
    findings = CT.check_thread_contracts({"thread": {}}, surface=surface)
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "thread.unlocked-shared-write"
    assert f.primitive == "_stop"
    assert f.file.endswith("flowmod_thread.py") and f.line == 7
    # a declared owner (or lock) clears it
    declared = {"thread": {f"{mod}:Srv": {
        "_stop": {"owner": "control", "reason": "GIL-atomic flag"}}}}
    assert CT.check_thread_contracts(declared, surface=surface) == []


# ---------------------------------------------------------------------------
# seeded violations: contracts family (table hygiene)
# ---------------------------------------------------------------------------

def test_stale_and_undocumented_entries_fire(tmp_path, monkeypatch):
    mod = _tmp_module(tmp_path, monkeypatch, "flowmod_hyg", """
        class Camp:
            def step(self):
                self.cursor = 1
    """)
    surface = {f"{mod}:Camp": []}
    state_a = CT.analyze_state(surface)
    con = {"state": {f"{mod}:Camp": {
        "deleted_attr": {"kind": "transient", "reason": "was real once"},
        "cursor": {"kind": "transient", "reason": ""},
    }}, "transfer": {}, "thread": {}}
    findings = CT.check_contract_hygiene(con, state_a, {}, {})
    by_rule = {(f.rule, f.primitive) for f in findings}
    assert ("contracts.stale-entry", "deleted_attr") in by_rule
    assert ("contracts.undocumented", "cursor") in by_rule


def test_overcounted_transfer_row_is_stale(tmp_path, monkeypatch):
    mod = _tmp_module(tmp_path, monkeypatch, "flowmod_hyg2", """
        def seam(x):
            return x.item()
    """)
    transfer_a = CT.analyze_transfer({"s": f"{mod}:seam"})
    con = {"state": {}, "thread": {}, "transfer": {f"{mod}:seam": [
        {"call": ".item()", "count": 3, "reason": "r"}]}}
    findings = CT.check_contract_hygiene(con, {}, transfer_a, {})
    assert [f.rule for f in findings] == ["contracts.stale-entry"]
    assert findings[0].count == 1 and findings[0].budget == 3


# ---------------------------------------------------------------------------
# the contracts.json ratchet
# ---------------------------------------------------------------------------

def test_contracts_rebaseline_refuses_growth():
    old = {"state": {}, "transfer": {}, "thread": {}}
    needed = {"state": {"m:C": {"x": {"kind": "transient", "reason": ""}}},
              "transfer": {}, "thread": {}}
    with pytest.raises(ValueError, match="GROW.*state:m:C.x"):
        CT.apply_contracts_rebaseline(old, needed)
    merged = CT.apply_contracts_rebaseline(old, needed,
                                           allow_regression=True)
    assert merged["state"]["m:C"]["x"]["reason"] == ""


def test_contracts_rebaseline_carries_reasons_and_shrinks():
    old = {"state": {"m:C": {
        "x": {"kind": "derived", "reason": "documented"},
        "gone": {"kind": "transient", "reason": "dead"}}},
        "transfer": {"m:f": [
            {"call": ".item()", "count": 2, "reason": "harvest"}]},
        "thread": {}}
    needed = {"state": {"m:C": {
        "x": {"kind": "transient", "reason": ""}}},
        "transfer": {"m:f": [
            {"call": ".item()", "count": 1, "reason": ""}]},
        "thread": {}}
    merged = CT.apply_contracts_rebaseline(old, needed)
    # old disposition + reason survive; the stale row drops; the
    # transfer count tightens to the measured value
    assert merged["state"]["m:C"]["x"] == \
        {"kind": "derived", "reason": "documented"}
    assert "gone" not in merged["state"]["m:C"]
    assert merged["transfer"]["m:f"] == [
        {"call": ".item()", "count": 1, "reason": "harvest"}]


def test_checked_in_contracts_fully_documented():
    """Zero undocumented allowlist entries in the shipped tables."""
    con = CT.load_contracts()
    for cls, attrs in con["state"].items():
        for attr, d in attrs.items():
            assert d["kind"] in CT.STATE_KINDS, (cls, attr)
            assert d["reason"].strip(), (cls, attr)
    for site, rows in con["transfer"].items():
        for row in rows:
            assert row["reason"].strip(), (site, row["call"])
    for cls, attrs in con["thread"].items():
        for attr, d in attrs.items():
            assert d.get("owner") or d.get("lock"), (cls, attr)
            assert d["reason"].strip(), (cls, attr)


# ---------------------------------------------------------------------------
# migrated seam rules keep their pins, now with provenance
# ---------------------------------------------------------------------------

def test_migrated_supervise_rule_has_provenance():
    bad = {"chunk": "wtf_tpu.supervise.ladder:DegradationLadder.apply"}
    findings = check_supervised_seams(sites=bad)
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "supervise.seam-routing" and "chunk" in f.message
    assert f.file.endswith("ladder.py") and f.line > 0


def test_migrated_telemetry_rule_keeps_primitive_shape():
    bad = {"exports": "wtf_tpu.fleet.telemetry:FleetTelemetry.write_exports"}
    findings = check_telemetry_seams(sites=bad)
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "telemetry.seam-serialization"
    assert "json.dumps(" in f.primitive
    assert f.file.endswith("telemetry.py") and f.line > 0


# ---------------------------------------------------------------------------
# findings plumbing: provenance + SARIF
# ---------------------------------------------------------------------------

def test_finding_provenance_optional_in_dict_and_str():
    bare = Finding(rule="r", entry="e", message="m")
    assert "file" not in bare.as_dict() and "(None" not in str(bare)
    located = Finding(rule="r", entry="e", message="m",
                      file="a/b.py", line=7)
    assert located.as_dict()["file"] == "a/b.py"
    assert str(located).endswith("(a/b.py:7)")


def test_sarif_document_shape():
    doc = to_sarif([
        Finding(rule="state.uncheckpointed", entry="e", message="m",
                file="a/b.py", line=7),
        Finding(rule="budget.kernel-count", entry="e", message="m"),
    ])
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "wtf-tpu-lint"
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == \
        {"state.uncheckpointed", "budget.kernel-count"}
    with_loc, without_loc = run["results"]
    assert with_loc["locations"][0]["physicalLocation"]["region"][
        "startLine"] == 7
    assert "locations" not in without_loc


# ---------------------------------------------------------------------------
# clean paths on the real tree
# ---------------------------------------------------------------------------

def test_contract_families_clean_ast_only():
    """The cheap (no-census) contract pass must stay clean and fast in
    tier-1: the checked-in tables exactly disposition the live tree."""
    findings, info = run_lint(
        families=["state", "transfer", "thread", "contracts"])
    assert findings == [], [str(f) for f in findings]
    assert "transfer_census" not in info  # census hides behind --deep


@pytest.mark.slow
def test_contract_families_clean_deep():
    """The full --deep pass: AST rules + the jaxpr host-transfer census,
    clean against the pins and inside the 60s wall budget (ISSUE 20)."""
    from wtf_tpu.telemetry import Registry

    registry = Registry()
    findings, info = run_lint(
        families=["state", "transfer", "thread", "contracts"],
        deep=True, registry=registry)
    assert findings == [], [str(f) for f in findings]
    pinned = {k: v for k, v in load_budgets()["host_transfer"].items()
              if k != "entry"}
    assert info["transfer_census"] == pinned
    assert sum(info["seconds"].values()) < 60
    dump = registry.dump()
    assert dump["analysis.transfer_census"]["total"] == pinned["total"]
