"""Device-resident x86 decode (wtf_tpu/interp/devdec.py).

The zero-host-steady-state contract: decode-cache misses inside a
megachunk window are serviced IN-GRAPH — page-walked 15-byte fetch,
batched decode, publish-order uop-table slot reservation — and the host
decoder stays the authoritative oracle: every device-published entry is
cross-checked bit-for-bit at harvest, encodings outside the device
subset park (stay NEED_DECODE) for in-order host service, and a
`--device-decode` campaign is byte-identical to the host-serviced
reference at equal seeds, single-device and on a mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wtf_tpu.analysis.trace import build_tlv_campaign
from wtf_tpu.cpu import uops as U
from wtf_tpu.cpu.decoder import decode
from wtf_tpu.cpu.uops import INT_FIELDS
from wtf_tpu.interp import devdec
from wtf_tpu.interp.uoptable import (
    M_BP, M_PFN0, M_PFN1, MU_DISP, MU_IMM, MU_RAW_HI, MU_RAW_LO,
    DecodeCache,
)
from wtf_tpu.mem.overlay import overlay_init
from wtf_tpu.mem.paging import translate, virt_read
from wtf_tpu.mem.physmem import IMAGE_IN_AXES, PhysMem, lane_image
from wtf_tpu.snapshot.synthetic import SyntheticSnapshotBuilder
from wtf_tpu.utils.hashing import hex_digest

MASK64 = (1 << 64) - 1
NEED_DECODE, RUNNING, PAGE_FAULT = 8, 0, 7

BUILD = dict(n_lanes=8, limit=20_000, chunk_steps=128, overlay_slots=16)

CODE = 0x140000000
# nop; mov eax,5; jnz +2; ret; inc ecx; ret   (all device-subset)
PROG = bytes.fromhex("90" "b805000000" "7502" "c3" "ffc1" "c3")
# a second chain: xor r8,r8; call +0; ret
PROG2 = bytes.fromhex("4d31c0" "e800000000" "c3")
X87 = bytes.fromhex("d8c1" "c3")          # fadd st(1): parks on device
# mov eax,5 placed 3 bytes before a page boundary -> the 5-byte
# encoding CROSSES into the next page (pfn1 != pfn0), then ret
SPLIT_OFF = 0xFFD


# -- fixture: synthetic snapshot + faithful host-service replication -------

@pytest.fixture(scope="module")
def snap():
    b = SyntheticSnapshotBuilder()
    b.write(CODE, PROG + b"\x00" * 16)
    b.write(CODE + 0x100, PROG2 + b"\x00" * 16)
    b.write(CODE + 0x200, X87 + b"\x00" * 16)
    b.write(CODE + SPLIT_OFF, bytes.fromhex("b805000000" "c3") + b"\x00" * 8)
    pages, cpu = b.build(rip=CODE, rsp=0x7FFE0F00)
    return PhysMem.from_pages(pages), int(cpu.cr3)


def _host_succs(u, at):
    nxt = (at + u.length) & MASK64
    if u.opc in (U.OPC_RET, U.OPC_IRET, U.OPC_HLT, U.OPC_INT,
                 U.OPC_INT1, U.OPC_INVALID, U.OPC_SYSCALL):
        return ()
    if u.opc == U.OPC_JMP:
        return ((nxt + u.imm) & MASK64,) if u.src_kind == U.K_IMM else ()
    if u.opc == U.OPC_JCC:
        return (nxt, (nxt + u.imm) & MASK64)
    if u.opc == U.OPC_CALL and u.src_kind == U.K_IMM:
        return (nxt, (nxt + u.imm) & MASK64)
    return (nxt,)


class _HostService:
    """runner._service_decode/_decode_at/_prefetch_block replicated over
    a synthetic snapshot — the parity reference for the device path."""

    def __init__(self, snap):
        self.mem, self.cr3 = snap

    def read(self, ov_lane, at, size):
        data, fault = virt_read(self.mem.image, ov_lane,
                                jnp.uint64(self.cr3), jnp.uint64(at), size)
        return bytes(np.asarray(data)), bool(fault)

    def pfn(self, ov_lane, at):
        t = translate(self.mem.image, ov_lane, jnp.uint64(self.cr3),
                      jnp.uint64(at))
        return int(t.gpa) >> 12, bool(t.ok)

    def decode_at(self, cache, ov_lane, rip):
        win, fault = self.read(ov_lane, rip, 15)
        pfn0, _ = self.pfn(ov_lane, rip)
        if fault:
            return False
        uop = decode(win, rip)
        pfn1, ok1 = self.pfn(ov_lane, (rip + max(uop.length - 1, 0))
                             & MASK64)
        if not ok1:
            pfn1 = pfn0
        cache.add(rip, uop, pfn0, pfn1)
        budget = 48
        work = list(_host_succs(uop, rip))
        while work and budget > 0:
            if cache.count >= cache.capacity - 64:
                return True
            at = work.pop()
            if cache.has(at):
                continue
            w, f = self.read(ov_lane, at, 15)
            p0, ok = self.pfn(ov_lane, at)
            if f or not ok:
                continue
            u = decode(w, at)
            if u.opc == U.OPC_INVALID:
                continue
            p1, ok1 = self.pfn(ov_lane, (at + max(u.length - 1, 0))
                               & MASK64)
            if not ok1:
                p1 = p0
            cache.add(at, u, p0, p1)
            budget -= 1
            work.extend(_host_succs(u, at))
        return True

    def service(self, cache, overlays, rips, statuses, upto):
        st = list(statuses)
        for lane in range(upto):
            if st[lane] != NEED_DECODE:
                continue
            ov_lane = jax.tree.map(lambda x: x[lane], overlays)
            rip = int(rips[lane])
            if not cache.has(rip):
                if not self.decode_at(cache, ov_lane, rip):
                    st[lane] = PAGE_FAULT
                    continue
            st[lane] = RUNNING
        return st

    def run_device(self, rips, statuses, seed_cache):
        n = len(rips)
        tab = seed_cache.device()
        overlays = overlay_init(n, 4)
        image = lane_image(self.mem.image, n)
        cr3s = jnp.full((n,), self.cr3, jnp.uint64)
        blocks = jax.vmap(
            devdec.lane_block,
            in_axes=(None, IMAGE_IN_AXES, 0, 0, 0, 0, None, None),
        )(tab, image, overlays, cr3s, jnp.asarray(rips, jnp.uint64),
          jnp.asarray(statuses, jnp.int32), jnp.zeros((2,), jnp.uint64),
          jnp.int32(0))
        out = devdec.commit_blocks(
            tab, jnp.int32(seed_cache.count), blocks,
            jnp.asarray(statuses, jnp.int32), seed_cache.capacity)
        return out, overlays


def _assert_table_matches(cache, out, statuses_host, n_committed_lanes):
    """Device table == host cache bit for bit over the committed prefix:
    entry ORDER (coverage-bit identity), keys, every Uop field, disp/imm,
    raw bytes, pfns, bp — plus lane statuses and probe consistency."""
    assert int(out.count) == cache.count
    tab = out.tab
    rip_l = np.asarray(tab.rip_l)
    mi = np.asarray(tab.meta_i32)
    mu = np.asarray(tab.meta_u64)
    for i in range(cache.count):
        key = (int(rip_l[i, 0]) | (int(rip_l[i, 1]) << 32)) & MASK64
        assert key == int(cache.rip[i]), f"entry {i} key"
        uop = cache.uops[key]
        for f, nm in enumerate(INT_FIELDS):
            assert int(mi[i, f]) == int(getattr(uop, nm)), \
                f"entry {i} ({key:#x}) field {nm}"
        for col, val in ((M_PFN0, cache.pfn0[i]), (M_PFN1, cache.pfn1[i]),
                         (M_BP, cache.bp[i])):
            assert int(mi[i, col]) == int(val), f"entry {i} meta col {col}"
        for col, val in ((MU_DISP, cache.disp[i]), (MU_IMM, cache.imm[i]),
                         (MU_RAW_LO, cache.raw_lo[i]),
                         (MU_RAW_HI, cache.raw_hi[i])):
            assert int(mu[i, col]) == int(val), f"entry {i} u64 col {col}"
        assert int(devdec._probe_entry(tab.hash_tab,
                                       jnp.uint64(key))) == i
    st_dev = [int(s) for s in np.asarray(out.status)]
    assert st_dev[:n_committed_lanes] == statuses_host[:n_committed_lanes]


# -- randomized-encoding differential: decode_window vs host decoder ------

def test_decode_window_differential():
    """Every encoding the device decoder claims to know must decode
    bit-identically to cpu.decoder.decode — across opcode-map/modrm
    skeletons with random prefix/REX dressing, fully random windows
    (mostly invalid), and prefix-run truncation cases.  Unknown
    encodings park; they are allowed, mismatches are not."""
    rng = np.random.default_rng(0x77F)
    prefix_sets = [
        b"", b"\x66", b"\x67", b"\xf0", b"\xf2", b"\xf3", b"\x64",
        b"\x65", b"\x2e", b"\x66\xf3", b"\xf2\xf3", b"\x66\x67\x65",
        b"\xf0\x66", b"\x66\x66",
    ]
    rexes = [b"", b"\x40", b"\x48", b"\x41", b"\x44", b"\x42", b"\x4f",
             b"\x45", b"\x4c"]
    cases = []
    for m in (0, 1):
        for op in range(256):
            for _ in range(4):
                digit, mod = rng.integers(8), rng.integers(4)
                rm = int(rng.choice([0, 3, 4, 5]))
                modrm = (int(mod) << 6) | (int(digit) << 3) | rm
                pfx = prefix_sets[rng.integers(len(prefix_sets))]
                rex = rexes[rng.integers(len(rexes))]
                body = bytes([0x0F, op] if m else [op]) + bytes([modrm])
                tail = rng.integers(0, 256, 14, dtype=np.uint8).tobytes()
                cases.append((pfx + rex + body + tail)[:15])
    for _ in range(3000):
        cases.append(rng.integers(0, 256, 15, dtype=np.uint8).tobytes())
    for _ in range(1000):
        n = rng.integers(8, 15)
        pfx = bytes(rng.choice(
            [0x66, 0x67, 0xF0, 0xF2, 0xF3, 0x64, 0x2E], n))
        body = rng.integers(0, 256, 15, dtype=np.uint8).tobytes()
        cases.append((pfx + body)[:15])

    wins = np.frombuffer(b"".join(cases), np.uint8).reshape(len(cases), 15)
    out = jax.jit(jax.vmap(devdec.decode_window))(jnp.asarray(wins))
    known = np.asarray(out.known)
    f = np.asarray(out.f)
    disp = np.asarray(out.disp)
    imm = np.asarray(out.imm)
    assert known.sum() > len(cases) // 10  # the subset is not vacuous
    for i, win in enumerate(cases):
        if not known[i]:
            continue
        hu = decode(win, 0)
        for j, name in enumerate(INT_FIELDS):
            assert int(f[i, j]) == int(getattr(hu, name)), \
                f"win={win.hex()} field {name}"
        assert int(disp[i]) == hu.disp, f"win={win.hex()} disp"
        assert int(imm[i]) == hu.imm, f"win={win.hex()} imm"


# -- service differential: blocks+commit vs replicated host service -------

def test_service_all_device_lanes_with_duplicate(snap):
    """All-decodable lanes, one duplicate rip, one non-needy lane: the
    committed table is the host service bit for bit (dup publishes
    once, in first-lane order)."""
    hs = _HostService(snap)
    rips = [CODE, CODE + 0x100, CODE, CODE + 6]
    sts = [NEED_DECODE, NEED_DECODE, NEED_DECODE, RUNNING]
    cache = DecodeCache()
    out, ovs = hs.run_device(rips, sts, DecodeCache())
    host_st = hs.service(cache, ovs, rips, sts, len(rips))
    _assert_table_matches(cache, out, host_st, len(rips))


def test_service_page_fault_lane(snap):
    """A lane at an unmapped rip faults exactly like the host service:
    PAGE_FAULT status, fault_gva=rip, mem-fault counter bumped — and
    the lanes around it still commit."""
    hs = _HostService(snap)
    rips = [CODE + 0x100, 0xDEAD0000, CODE]
    sts = [NEED_DECODE] * 3
    cache = DecodeCache()
    out, ovs = hs.run_device(rips, sts, DecodeCache())
    host_st = hs.service(cache, ovs, rips, sts, len(rips))
    _assert_table_matches(cache, out, host_st, len(rips))
    assert bool(np.asarray(out.fault_mask)[1])
    assert int(np.asarray(out.fault_gva)[1]) == 0xDEAD0000
    assert int(np.asarray(out.mem_fault_inc)[1]) == 1


def test_service_park_all_rest(snap):
    """An encoding outside the device subset (x87) parks its lane AND
    every later needy lane — publish order is lane order, so nothing
    may leapfrog a parked lane.  Parked first => empty table; parked
    mid => the prefix commits and matches the host."""
    hs = _HostService(snap)
    out, _ = hs.run_device([CODE + 0x200, CODE],
                           [NEED_DECODE, NEED_DECODE], DecodeCache())
    assert int(out.count) == 0
    assert list(np.asarray(out.parked)) == [True, True]
    assert [int(s) for s in np.asarray(out.status)] == [NEED_DECODE] * 2

    cache = DecodeCache()
    out, ovs = hs.run_device([CODE, CODE + 0x200, CODE + 0x100],
                             [NEED_DECODE] * 3, DecodeCache())
    host_st = hs.service(cache, ovs, [CODE, CODE + 0x200, CODE + 0x100],
                         [NEED_DECODE] * 3, 1)
    _assert_table_matches(cache, out, host_st, 1)
    assert list(np.asarray(out.parked)) == [False, True, True]


def test_service_page_boundary_crossing(snap):
    """An encoding whose bytes straddle a page boundary publishes with
    pfn1 != pfn0 — the split-fetch pfn facts must match the host's
    per-byte translate walk exactly."""
    hs = _HostService(snap)
    rip = CODE + SPLIT_OFF
    cache = DecodeCache()
    out, ovs = hs.run_device([rip], [NEED_DECODE], DecodeCache())
    host_st = hs.service(cache, ovs, [rip], [NEED_DECODE], 1)
    _assert_table_matches(cache, out, host_st, 1)
    idx = int(np.asarray(out.count)) and 0
    mi = np.asarray(out.tab.meta_i32)
    assert int(mi[idx, M_PFN1]) == int(mi[idx, M_PFN0]) + 1


def test_service_warm_resume_and_smc_redecode(snap):
    """Warm start: lanes re-missing cached rips resume RUNNING without
    publishing (count unchanged).  SMC re-decode parity: a host
    cache.update (the SMC service path) rewrites the entry IN PLACE —
    same index — and the refreshed device table carries the updated
    fields, so a later device round still resumes against it."""
    hs = _HostService(snap)
    cache = DecodeCache()
    hs.service(cache, overlay_init(2, 4), [CODE, CODE + 0x100],
               [NEED_DECODE] * 2, 2)
    n0 = cache.count
    out, _ = hs.run_device([CODE, CODE + 0x100], [NEED_DECODE] * 2, cache)
    assert int(out.count) == n0
    assert [int(s) for s in np.asarray(out.status)] == [RUNNING] * 2

    # SMC: host re-decodes new bytes at CODE (inc ecx; ret lives there
    # in this fiction) and updates the shared entry in place
    new_uop = decode(bytes.fromhex("ffc1") + b"\x90" * 13, CODE)
    idx = cache.entry_index(CODE)
    cache.update(CODE, new_uop, cache.pfn0[idx], cache.pfn1[idx])
    assert cache.entry_index(CODE) == idx  # in-place, index stable
    out2, _ = hs.run_device([CODE, CODE + 0x100], [NEED_DECODE] * 2, cache)
    assert int(out2.count) == cache.count  # still no re-publish
    mi = np.asarray(out2.tab.meta_i32)
    for f, nm in enumerate(INT_FIELDS):
        assert int(mi[idx, f]) == int(getattr(new_uop, nm))


# -- campaign integration: --device-decode bit-identity -------------------

def _fingerprint(loop) -> dict:
    cov, edge = loop.backend.coverage_state()
    return {
        "cov": cov.tobytes(),
        "edge": edge.tobytes(),
        "cov_bits": loop._coverage(),
        "corpus_order": [hex_digest(d) for d in loop.corpus],
        "crashes": sorted(loop.crash_names),
        "buckets": sorted(loop.crash_buckets),
        "testcases": loop.stats.testcases,
        "timeouts": loop.stats.timeouts,
        "new_coverage": loop.stats.new_coverage,
    }


def _campaign(megachunk: int, runs: int, seed: int = 0x5EED, **kw):
    cfg = dict(BUILD)
    cfg.update(kw)
    loop = build_tlv_campaign(mutator="devmangle", seed=seed,
                              megachunk=megachunk, **cfg)
    loop.fuzz(runs)
    return loop


def test_device_decode_campaign_bit_identical():
    """The acceptance bar: a cold-cache `--device-decode` megachunk
    campaign is byte-identical to the host-serviced reference at equal
    seeds — coverage/edge bytes, corpus digests, crash buckets — with
    every decode entry device-published (zero host decode services),
    zero cross-check mismatches, and the checkpoint entry stream
    carrying identical indices."""
    runs = BUILD["n_lanes"] * 12
    ref = _campaign(4, runs)
    dd = _campaign(4, runs, device_decode=True)
    assert _fingerprint(dd) == _fingerprint(ref)
    reg = dd.backend.registry
    assert reg.counter("devdec.published").value > 0
    assert reg.counter("devdec.crosscheck_mismatches").value == 0
    assert reg.counter("devdec.zero_host_windows").value > 0
    # zero-host steady state on this target: the device serviced every
    # miss, the host decoder ran only as the cross-check oracle
    assert dd.backend.runner.stats["decodes"] == 0
    assert ref.backend.runner.stats["decodes"] > 0
    # device-published entries round-trip the checkpoint stream with
    # identical indices (coverage bit == entry index)
    ref_entries = list(ref.backend.runner.cache.checkpoint_entries())
    dd_entries = list(dd.backend.runner.cache.checkpoint_entries())
    assert dd_entries == ref_entries


def test_device_decode_pipelined_harvest_parity():
    """Pipelined harvest: steady-state windows prelaunch batch N+1
    before batch N's harvest completes; adopted speculative windows
    must leave the campaign byte-identical to the unpipelined reference
    (the prelaunch is dropped, not patched, on any operand drift)."""
    runs = BUILD["n_lanes"] * 24
    ref = _campaign(4, runs)
    dd = _campaign(4, runs, device_decode=True)
    assert _fingerprint(dd) == _fingerprint(ref)
    reg = dd.backend.registry
    assert reg.counter("megachunk.prelaunched").value > 0
    assert reg.counter("megachunk.prelaunch_hits").value > 0


def test_device_decode_mesh_parity():
    """Decode-slot parity on the forced 8-device mesh: the replicated
    commit (all-gathered blocks, identical sequential replay per shard)
    must yield the same entry indices — the campaign fingerprint and
    the decode cache match the single-device run exactly."""
    if len(jax.devices()) < 8:
        pytest.skip("needs XLA_FLAGS=--xla_force_host_platform_"
                    "device_count=8 (make mesh-smoke environment)")
    runs = BUILD["n_lanes"] * 6
    single = _campaign(3, runs, device_decode=True)
    mesh = _campaign(3, runs, mesh_devices=8, device_decode=True)
    assert _fingerprint(mesh) == _fingerprint(single)
    assert (list(mesh.backend.runner.cache.checkpoint_entries())
            == list(single.backend.runner.cache.checkpoint_entries()))
    assert mesh.backend.registry.counter(
        "devdec.crosscheck_mismatches").value == 0


@pytest.mark.slow
def test_device_decode_checkpoint_killpoint_sweep(tmp_path):
    """PR-8 crash-safety with device-published decode entries: kill at
    every interior batch boundary, resume, end bit-identical — the
    restored cache (device-published entries included) must rebuild
    the same uop-table indices or every later coverage bit shifts."""
    from wtf_tpu.resume import load_campaign, restore_campaign
    from wtf_tpu.testing.faultinject import fuzz_until_killed

    batches = 4
    runs = BUILD["n_lanes"] * batches
    ref = _campaign(4, runs, device_decode=True)
    ref_fp = _fingerprint(ref)
    assert ref_fp["cov_bits"] > 0

    for kill_at in range(1, batches):
        ckpt = tmp_path / f"kill{kill_at}"
        victim = build_tlv_campaign(mutator="devmangle", seed=0x5EED,
                                    megachunk=4, device_decode=True,
                                    **BUILD)
        victim.checkpoint_dir, victim.checkpoint_every = ckpt, 1
        fuzz_until_killed(victim, runs, kill_at_batch=kill_at)

        resumed = build_tlv_campaign(mutator="devmangle", seed=0x5EED,
                                     megachunk=4, device_decode=True,
                                     **BUILD)
        state, fell_back = load_campaign(ckpt)
        assert not fell_back
        assert restore_campaign(resumed, state, ckpt) == kill_at
        resumed.fuzz(runs)
        assert _fingerprint(resumed) == ref_fp, \
            f"kill at batch {kill_at}: state diverged"


# ---------------------------------------------------------------------------
# telemetry: the device-decode report section
# ---------------------------------------------------------------------------

def test_telemetry_report_device_decode_section(tmp_path):
    """The report surfaces the zero-host story: published entries,
    cross-check verdict, zero-host window lengths, and the harvest
    overlap share — and stays None for runs that never device-decoded."""
    import sys
    from pathlib import Path as _P

    sys.path.insert(0, str(_P(__file__).parent.parent / "tools"))
    import telemetry_report

    from wtf_tpu.telemetry import EventLog, Registry

    tdir = tmp_path / "telemetry"
    events = EventLog(tdir / "events.jsonl")
    registry = Registry()
    registry.counter("devdec.published").inc(53)
    registry.counter("devdec.serviced_lanes").inc(61)
    registry.counter("devdec.parked_lanes").inc(2)
    registry.counter("devdec.service_rounds").inc(9)
    registry.counter("devdec.zero_host_windows").inc(7)
    registry.counter("devdec.zero_host_batches").inc(89)
    registry.counter("devdec.crosscheck_mismatches").inc(0)
    registry.counter("runner.decodes").inc(0)
    registry.counter("megachunk.windows").inc(8)
    registry.counter("megachunk.prelaunched").inc(5)
    registry.counter("megachunk.prelaunch_hits").inc(4)
    registry.counter("megachunk.prelaunch_dropped").inc(1)
    events.emit("run-end", metrics=registry.dump())
    events.close()
    summary = telemetry_report.summarize(tdir)
    ddc = summary["device_decode"]
    assert ddc["published"] == 53
    assert ddc["crosscheck_mismatches"] == 0
    assert ddc["host_decode_services"] == 0
    assert ddc["zero_host_windows"] == 7
    assert ddc["zero_host_mean_batches"] == round(89 / 7, 1)
    assert ddc["harvest_overlap_share"] == 0.5
    telemetry_report._print_human(summary)  # must not raise

    # a host-serviced run has no devdec signal -> section stays None
    bare = tmp_path / "bare"
    events = EventLog(bare / "events.jsonl")
    registry = Registry()
    registry.counter("runner.decodes").inc(12)
    events.emit("run-end", metrics=registry.dump())
    events.close()
    assert telemetry_report.summarize(bare)["device_decode"] is None
