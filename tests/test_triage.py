"""Batched triage engine (wtf_tpu/triage) on the conftest's virtual
CPU devices.

The acceptance contract (ISSUE 11): minimize converges to a
known-minimal demo_tlv reproducer of the SAME crash bucket; distill's
per-testcase edge attribution matches a host recount exactly and its
minset preserves aggregate coverage; vbreak captures equal the EmuCpu
oracle state at the armed instruction; and all three are bit-identical
on a mesh vs a single device.  Plus the crash-bucket satellite: two
distinct crashers never merge buckets, even when their filename-grade
names collide.
"""

import json
import random
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

from wtf_tpu.backend.emu import EmuBackend
from wtf_tpu.backend.tpu import TpuBackend
from wtf_tpu.core.results import Crash, Ok
from wtf_tpu.fuzz.corpus import Corpus
from wtf_tpu.fuzz.loop import FuzzLoop
from wtf_tpu.fuzz.mutator import ByteMutator
from wtf_tpu.harness import demo_tlv
from wtf_tpu.meshrun import MeshBackend
from wtf_tpu.triage import (
    ReplayCore, distill, minimize, oracle_capture, perturbations, vbreak,
)
from wtf_tpu.triage.bucket import bucket_of

# same shapes as tests/test_meshrun.py so executor compiles share the
# in-process jit cache and the persistent compilation cache
SMALL = dict(uop_capacity=1 << 10, overlay_slots=16, edge_bits=12,
             chunk_steps=8)
N_LANES = 16
LIMIT = 20000

# The canonical crasher family: a type-3 record copies 32 bytes into an
# 8-byte stack buffer — payload offsets 16..23 smash the saved rbp,
# 24..31 the return address (demo_tlv._GUEST_ASM).  `ret` then fetches
# from 0x4141... (non-canonical) -> execute fault.
SMASH = bytes([3, 32]) + bytes(range(65, 89)) + b"\x41" * 8
CRASHER = b"\x01\x02XY" + SMASH + b"\x01\x03ZZZ"
MINIMAL = bytes([3, 32]) + bytes(24) + b"\x41" * 8

CORPUS = [
    b"\x01\x02XY",                  # type-1 only
    b"\x01\x03ABC",                 # type-1 only (coverage-subsumed)
    b"\x02\x08QQQQQQQQ",            # type-2 only
    b"\x01\x02XY\x02\x08WWWWWWWW",  # types 1+2 (covers both)
    b"\x03\x04abcd",                # type-3 short copy (no crash)
]


def _backend(cls=TpuBackend, **kwargs):
    backend = cls(demo_tlv.build_snapshot(), n_lanes=N_LANES, limit=LIMIT,
                  **SMALL, **kwargs)
    backend.initialize()
    demo_tlv.TARGET.init(backend)
    return backend


@pytest.fixture(scope="module")
def backend():
    return _backend()


@pytest.fixture(scope="module")
def mesh_backend():
    return _backend(cls=MeshBackend, mesh_devices=8)


@pytest.fixture(scope="module")
def emu_backend():
    backend = EmuBackend(demo_tlv.build_snapshot(), limit=LIMIT)
    backend.initialize()
    demo_tlv.TARGET.init(backend)
    return backend


def _reset_coverage(backend):
    """Zero the backend's aggregate bitmaps: tests asserting absolute
    new-coverage semantics must not see earlier tests' merges (the
    module-scoped backend trades isolation for compile reuse)."""
    cov, edge = backend.coverage_state()
    backend.restore_coverage_state(np.zeros_like(cov), np.zeros_like(edge))


# ---------------------------------------------------------------------------
# crash buckets
# ---------------------------------------------------------------------------

def test_distinct_crashers_never_merge_buckets(backend):
    """The satellite pin: (kind, faulting RIP, top-of-stack hash) keeps
    distinct crashers apart — including two whose filename-grade names
    COLLIDE (same fault address, different smashed stacks)."""
    # A/B: different smashed return addresses -> different faulting RIP
    a = bytes([3, 32]) + bytes(24) + b"\x41" * 8
    b = bytes([3, 32]) + bytes(24) + b"\x42" * 8
    # C/D: SAME return address (same Crash.name) but the copy runs past
    # the return slot, planting different bytes at [rsp..] -> the
    # top-of-stack hash must split them
    c = bytes([3, 40]) + bytes(24) + b"\x41" * 8 + b"\xAA" * 8
    d = bytes([3, 40]) + bytes(24) + b"\x41" * 8 + b"\xBB" * 8
    core = ReplayCore(backend, demo_tlv.TARGET)
    sweep = core.replay([a, b, c, d], want_buckets=True)
    assert all(isinstance(r, Crash) for r in sweep.results)
    assert sweep.results[2].name == sweep.results[3].name  # names collide
    buckets = [sweep.buckets[i] for i in range(4)]
    assert len(set(buckets)) == 4, buckets


def test_fuzz_loop_dedups_by_bucket(backend, tmp_path):
    """FuzzLoop's harvest and the triage helper share one bucket: the
    name-colliding pair lands as TWO buckets (and the crash event says
    new=True for each first sighting)."""
    c = bytes([3, 40]) + bytes(24) + b"\x41" * 8 + b"\xAA" * 8
    d = bytes([3, 40]) + bytes(24) + b"\x41" * 8 + b"\xBB" * 8
    loop = FuzzLoop(backend, demo_tlv.TARGET,
                    ByteMutator(random.Random(1), 128),
                    Corpus(), crashes_dir=tmp_path / "crashes")
    batch = [c, d]
    results = backend.run_batch(batch, demo_tlv.TARGET)
    for lane, (data, result) in enumerate(zip(batch, results)):
        loop._harvest_lane(lane, data, result)
    demo_tlv.TARGET.restore()
    backend.restore()
    assert len(loop.crash_names) == 1          # filenames collide...
    assert len(loop.crash_buckets) == 2        # ...buckets do not


# ---------------------------------------------------------------------------
# minimize
# ---------------------------------------------------------------------------

def test_minimize_converges_to_known_minimal(backend):
    result = minimize(backend, demo_tlv.TARGET, CRASHER)
    assert result.data == MINIMAL
    assert result.from_len == len(CRASHER)
    assert len(result.data) < len(CRASHER)
    # the minimized reproducer still reproduces the SAME bucket (the
    # minimizer verified this internally; re-check independently)
    core = ReplayCore(backend, demo_tlv.TARGET)
    sweep = core.replay([CRASHER, result.data], want_buckets=True)
    assert sweep.buckets[0] == sweep.buckets[1] == result.bucket
    # "a handful of dispatches": bisection, not per-candidate replay
    assert result.dispatches <= 40
    assert result.candidates > len(CRASHER)  # real batched storm


def test_minimize_rejects_non_crasher(backend):
    with pytest.raises(ValueError, match="does not reproduce"):
        minimize(backend, demo_tlv.TARGET, b"\x01\x02XY")


# ---------------------------------------------------------------------------
# distill
# ---------------------------------------------------------------------------

def test_distill_attribution_matches_host_recount(backend):
    _reset_coverage(backend)
    result = distill(backend, demo_tlv.TARGET, CORPUS)
    sweep = result.sweep
    planes = np.concatenate([sweep.cov, sweep.edge], axis=1)
    credit = np.concatenate([sweep.credit_cov, sweep.credit_edge], axis=1)
    union = np.zeros(planes.shape[1], np.uint32)
    for i in range(len(CORPUS)):
        expected = planes[i] & ~union
        np.testing.assert_array_equal(
            credit[i], expected,
            err_msg=f"in-graph first-hit credit diverges at testcase {i}")
        union |= planes[i]
    # credit flags == the backend merge's new-coverage flags (the old
    # minset keep rule) — one prefix-credit semantics everywhere
    np.testing.assert_array_equal(sweep.new_lane, result.credit_bits > 0)


def test_distill_cover_is_exact_and_minimal(backend):
    result = distill(backend, demo_tlv.TARGET, CORPUS)
    # set-cover invariant: kept aggregate == full corpus aggregate
    assert result.kept_bits == result.total_bits > 0
    assert 0 < len(result.keep) < len(CORPUS)
    # exact attribution can only improve on prefix credit
    assert len(result.keep) <= len(result.prefix_keep)
    # subsumed seeds carry zero exact credit
    assert result.credit_bits[1] == 0


def test_minset_rides_the_replay_core(backend):
    """FuzzLoop.minset (campaign --runs 0) and distill share one
    execution path and one keep rule: minset's kept set == the
    prefix-credit indices, stats accounted as before."""
    _reset_coverage(backend)
    corpus = Corpus()
    for data in CORPUS:
        corpus.add(data)
    ordered = list(corpus)
    loop = FuzzLoop(backend, demo_tlv.TARGET,
                    ByteMutator(random.Random(1), 128), corpus)
    # CampaignStats counters live in the backend's (module-shared)
    # registry — assert the deltas this minset contributed
    testcases0 = loop.stats.testcases
    newcov0 = loop.stats.new_coverage
    kept = loop.minset(outputs_dir=None)
    result = distill(backend, demo_tlv.TARGET, ordered)
    from wtf_tpu.utils.hashing import hex_digest

    assert kept.digests == {hex_digest(ordered[i])
                            for i in result.prefix_keep}
    assert loop.stats.testcases - testcases0 == len(ordered)
    assert loop.stats.new_coverage - newcov0 == len(result.prefix_keep)


# ---------------------------------------------------------------------------
# vbreak
# ---------------------------------------------------------------------------

# `next_record` (the loop head `cmp r8, r9`): push+mov+sub+mov+lea+xor
# prefix = 18 bytes of _GUEST_CODE
NEXT_RECORD = demo_tlv.CODE_GVA + 18


def test_vbreak_capture_equals_oracle(backend, emu_backend):
    data = b"\x01\x02XY\x02\x08WWWWWWWW"
    testcases = perturbations(data, 4)
    captures, results = vbreak(backend, demo_tlv.TARGET, testcases,
                               NEXT_RECORD, hit=2)
    assert captures[0] is not None  # the unperturbed baseline captures
    for i, data_i in enumerate(testcases):
        oc = oracle_capture(emu_backend, demo_tlv.TARGET, data_i,
                            NEXT_RECORD, hit=2)
        c = captures[i]
        # a perturbation may divert before the 2nd arrival — device and
        # oracle must AGREE on that too
        assert (c is None) == (oc is None), f"capture parity, tc {i}"
        if c is None:
            continue
        assert isinstance(results[i], Ok)
        assert c.rip == oc.rip == NEXT_RECORD
        assert c.gpr == oc.gpr, f"gpr mismatch on testcase {i}"
        assert c.rflags == oc.rflags
        assert c.icount == oc.icount > 0
        assert c.mem == oc.mem and len(c.mem) > 0
    # the second arrival really is mid-parse: r8 advanced past record 0
    assert captures[0].gpr[8] > demo_tlv.INPUT_GVA


def test_vbreak_unreached_rip_reports_natural_result(backend):
    # a crasher never returns to the loop head a 3rd time
    captures, results = vbreak(backend, demo_tlv.TARGET, [MINIMAL],
                               NEXT_RECORD, hit=99)
    assert captures == [None]
    assert isinstance(results[0], Crash)
    # the armed bp is disarmed again: plain replay is unaffected
    sweep = ReplayCore(backend, demo_tlv.TARGET).replay([CORPUS[0]])
    assert isinstance(sweep.results[0], Ok)


def test_vbreak_collision_with_target_bp(backend):
    with pytest.raises(ValueError, match="already armed"):
        vbreak(backend, demo_tlv.TARGET, [CORPUS[0]], demo_tlv.FINISH_GVA)


# ---------------------------------------------------------------------------
# mesh bit-parity
# ---------------------------------------------------------------------------

def test_mesh_bit_parity_all_three(backend, mesh_backend):
    """--mesh-devices 8 vs single device: minimize returns the same
    bytes/bucket/dispatch count, distill the same keep sets and credit
    ledger, vbreak the same captures — bit-identical triage."""
    a = minimize(backend, demo_tlv.TARGET, CRASHER)
    b = minimize(mesh_backend, demo_tlv.TARGET, CRASHER)
    assert a.data == b.data == MINIMAL
    assert a.bucket == b.bucket
    assert (a.rounds, a.dispatches, a.simplified) == \
        (b.rounds, b.dispatches, b.simplified)

    da = distill(backend, demo_tlv.TARGET, CORPUS)
    db = distill(mesh_backend, demo_tlv.TARGET, CORPUS)
    assert da.keep == db.keep
    assert da.prefix_keep == db.prefix_keep
    np.testing.assert_array_equal(da.credit_bits, db.credit_bits)
    assert (da.total_bits, da.kept_bits) == (db.total_bits, db.kept_bits)

    data = b"\x01\x02XY\x02\x08WWWWWWWW"
    ca, _ = vbreak(backend, demo_tlv.TARGET, perturbations(data, 3),
                   NEXT_RECORD, hit=2)
    cb, _ = vbreak(mesh_backend, demo_tlv.TARGET, perturbations(data, 3),
                   NEXT_RECORD, hit=2)
    for x, y in zip(ca, cb):
        assert (x.gpr, x.rflags, x.icount, x.mem) == \
            (y.gpr, y.rflags, y.icount, y.mem)


# ---------------------------------------------------------------------------
# lint + report satellites
# ---------------------------------------------------------------------------

def test_lint_pins_triage_chunk_identity(monkeypatch):
    from wtf_tpu.analysis import rules
    from wtf_tpu.triage import replay

    assert rules.check_triage_chunk() == []
    monkeypatch.setattr(replay, "REPLAY_CHUNK_FACTORY",
                        lambda n, donate: None)
    found = rules.check_triage_chunk()
    assert [f.rule for f in found] == ["budget.triage-chunk"]


def test_lint_pins_triage_dtype_exports():
    """Every triage ported path has a recipe (dtype.unpinned fires for a
    seeded rogue export, stays silent for the real ones)."""
    from wtf_tpu.analysis.rules import run_dtype_family
    from wtf_tpu.triage import candidates

    clean = run_dtype_family(exports=dict(candidates.PORTED_LIMB_PATHS),
                             compile_paths=False)
    assert clean == []
    seeded = run_dtype_family(
        exports={**candidates.PORTED_LIMB_PATHS,
                 "triage.rogue_path": lambda x: x},
        compile_paths=False)
    assert [f.rule for f in seeded] == ["dtype.unpinned"]


def test_report_triage_section(tmp_path):
    from telemetry_report import summarize

    events = tmp_path / "events.jsonl"
    metrics = {
        "triage.candidates": 742, "triage.dispatches": 28,
        "triage.minimizations": 1, "triage.minimize_rounds": 3,
        "triage.bytes_removed": 9, "triage.minset_before": 5,
        "triage.minset_after": 2, "triage.captures": 4,
        "triage.crashes": 300,
    }
    with events.open("w") as fh:
        fh.write(json.dumps({"ts": 1.0, "seq": 0, "type": "run-start"})
                 + "\n")
        fh.write(json.dumps({"ts": 11.0, "seq": 1, "type": "run-end",
                             "metrics": metrics}) + "\n")
    s = summarize(events)
    tri = s["triage"]
    assert tri["candidates"] == 742
    assert tri["dispatches_per_minimization"] == 28.0
    assert tri["minset_before"] == 5 and tri["minset_after"] == 2
    assert tri["captures"] == 4
    # quiet campaigns stay quiet
    with events.open("w") as fh:
        fh.write(json.dumps({"ts": 1.0, "seq": 0, "type": "run-start"})
                 + "\n")
        fh.write(json.dumps({"ts": 2.0, "seq": 1, "type": "run-end",
                             "metrics": {}}) + "\n")
    assert summarize(events)["triage"] is None
