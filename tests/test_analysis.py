"""Graph-invariant linter tests (wtf_tpu/analysis).

Two layers:

  * negative paths (ISSUE 5 satellite): each rule family gets a seeded
    violation — a u64 op in a "ported" path, a gather over budget, a
    weak-typed operand / value captured in a trace, a pstep/step opclass
    mismatch — and must fire its NAMED rule with actionable provenance
    (rule + entry point + primitive);
  * clean paths: the cheap families (parity, donation policy, seam)
    against the real tree; the full `run_lint` (which compiles the step
    ladder, ~30s) runs in the slow tier — tier-1 covers the dtype family
    through tests/test_limbs.py instead.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wtf_tpu.analysis.findings import Finding
from wtf_tpu.analysis.parity import (
    check_fused_parity, kernel_hot_opclasses, step_unsupported_opclasses,
)
from wtf_tpu.analysis.rules import (
    check_budget, check_donation_aliasing, check_no_u64,
    check_runner_donation_policy, check_seam_bitcast_only,
    check_signature_stable, check_strong_inputs, count_data_dependent_ops,
    load_budgets, run_dtype_family, run_lint,
)
from wtf_tpu.analysis.trace import compiled_hlo, lower_jit

P = (jnp.uint32(0x55667788), jnp.uint32(0x11223344))


# ---------------------------------------------------------------------------
# dtype family
# ---------------------------------------------------------------------------

def test_no_u64_rule_fires_on_seeded_u64_op():
    """A 64-bit add smuggled into a 'ported' path must fire dtype.no-u64
    with the dtype named and the entry point attached."""
    def bad(p):
        wide = p[0].astype(jnp.uint64) | (p[1].astype(jnp.uint64) << 32)
        return wide + jnp.uint64(1)

    findings = check_no_u64(bad, P, entry="seeded.bad_path")
    assert findings, "seeded u64 op not detected"
    assert all(f.rule == "dtype.no-u64" for f in findings)
    assert any(f.primitive == "u64" for f in findings)
    assert all(f.entry == "seeded.bad_path" for f in findings)


def test_no_u64_rule_clean_on_limb_path():
    from wtf_tpu.interp import limbs as L

    assert check_no_u64(L.add64, P, P, entry="limbs.add64") == []


def test_seam_rule_allows_bitcast_forbids_arith():
    from wtf_tpu.interp import limbs as L

    v32 = jnp.zeros((4, 2), jnp.uint32)
    assert check_seam_bitcast_only(L.pack_u64, v32,
                                   entry="limbs.pack_u64") == []

    def leaky(x32):
        return L.pack_u64(x32) + jnp.uint64(1)   # arithmetic on the seam

    findings = check_seam_bitcast_only(leaky, v32, entry="seeded.seam")
    assert any(f.rule == "dtype.seam-bitcast-only" and f.primitive == "add"
               for f in findings), findings


def test_unpinned_ported_path_is_a_finding():
    """A path exported via step.PORTED_LIMB_PATHS without an argument
    recipe in the analyzer must fail the lint, not silently dodge the
    zero-u64 pin."""
    from wtf_tpu.interp import step as S

    exports = dict(S.PORTED_LIMB_PATHS)
    exports["step.freshly_ported_thing"] = lambda x: x
    # compile_paths=False: the completeness check alone (the compiled
    # no-u64 sweep over the real recipes runs in test_limbs / the lint)
    findings = run_dtype_family(exports=exports, compile_paths=False)
    assert [(f.rule, f.entry) for f in findings] == [
        ("dtype.unpinned", "step.freshly_ported_thing")]
    assert run_dtype_family(compile_paths=False) == []


# ---------------------------------------------------------------------------
# budget family
# ---------------------------------------------------------------------------

def test_budget_rule_fires_on_extra_gather():
    """A real mini-compile with a data-dependent gather, checked against
    a zero budget: the rule must name the op kind, the measured count,
    and the pinned value."""
    def gathery(img, idx):
        return img[idx] + img[idx + 1]

    text = compiled_hlo(gathery, jnp.arange(64, dtype=jnp.int32),
                        jnp.int32(3))
    counts = count_data_dependent_ops(text)
    assert counts["total"] >= 1, counts
    budget = {k: 0 for k in counts}
    findings = check_budget(counts, budget, entry="seeded.gathery")
    assert findings
    f = findings[-1]           # the "total" row
    assert f.rule == "budget.kernel-count"
    assert f.primitive == "total"
    assert f.count == counts["total"] and f.budget == 0
    assert "rebaseline" in f.message


def test_budget_rule_fires_on_improvement_too():
    """The pin is exact: dropping below budget is also a finding (force a
    conscious re-baseline), and a matching tree is clean."""
    counts = {"gather": 2, "dynamic-slice": 0, "dynamic-update-slice": 0,
              "scatter": 0, "total": 2}
    assert check_budget(counts, dict(counts), entry="e") == []
    low = check_budget(counts, {**counts, "gather": 5, "total": 5},
                       entry="e")
    assert {f.primitive for f in low} == {"gather", "total"}
    assert all("under" in f.message for f in low)


def test_checked_in_budget_matches_perf_record():
    """analysis/budgets.json pins the step ladder at the PERF.md
    round-18 math: 165 surviving data-dependent kernels (78/59/28 —
    round 16's 166 minus the uop-fetch rip_l gather that the packed
    one-gather lookup made dead)."""
    budget = load_budgets()["xla_step"]
    assert budget["total"] == 165
    assert (budget["gather"], budget["dynamic-slice"],
            budget["dynamic-update-slice"]) == (78, 59, 28)
    # the tenant ladder is the SAME program over a stacked image table
    assert load_budgets()["tenant_chunk"]["total"] == 165
    # the in-graph decode service compiles as its own pinned graph
    # (round 18) so decoder growth is a lint finding, not silent fusion
    assert load_budgets()["decode_service"]["total"] == 268


def test_rebaseline_is_a_ratchet():
    """--rebaseline refuses to record a budget INCREASE without
    --allow-regression (ISSUE 14): decrements re-pin freely, increments
    raise naming every offending entry, and allow_regression=True
    records them consciously."""
    from wtf_tpu.analysis import apply_rebaseline

    old = {"xla_step": {"total": 166, "gather": 79},
           "mesh_chunk": {"total": 1}}
    # a decrease (and a brand-new entry) merge freely
    merged = apply_rebaseline(old, {"xla_step": {"total": 150},
                                    "new_entry": {"total": 9}})
    assert merged["xla_step"]["total"] == 150
    assert merged["new_entry"]["total"] == 9
    # an increase is refused, naming the entry and both totals
    with pytest.raises(ValueError, match="xla_step: 166 -> 170"):
        apply_rebaseline(old, {"xla_step": {"total": 170}})
    # ... unless consciously allowed
    merged = apply_rebaseline(old, {"xla_step": {"total": 170}},
                              allow_regression=True)
    assert merged["xla_step"]["total"] == 170


# ---------------------------------------------------------------------------
# recompile family
# ---------------------------------------------------------------------------

def test_weak_type_rule_fires_on_python_scalar_operand():
    findings = check_strong_inputs((jnp.zeros(3, jnp.uint32), 1.5),
                                   entry="seeded.executor")
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "recompile.weak-type"
    assert "weak" in f.primitive and f.entry == "seeded.executor"
    # committed dtypes are clean
    assert check_strong_inputs(
        (jnp.zeros(3, jnp.uint32), jnp.uint64(5)), entry="e") == []


def test_signature_instability_rule_fires_on_value_capture():
    """A python value captured by the trace (the retrace-per-value
    hazard) shows up as differing lowerings of the 'same' executor."""
    state = {"k": 1.0}

    def capturing(x):
        return x * state["k"]

    # fresh lambda per lowering: jax's trace cache keys on function
    # identity, and the real probe (trace.step_executor_lowering) re-jits
    # a fresh closure for the same reason
    x = jnp.zeros(4, jnp.float32)
    text_a = lower_jit(lambda v: capturing(v), x).as_text()
    state["k"] = 2.0
    text_b = lower_jit(lambda v: capturing(v), x).as_text()
    findings = check_signature_stable(text_a, text_b,
                                      entry="seeded.capturing")
    assert len(findings) == 1
    assert findings[0].rule == "recompile.signature-unstable"
    # and a pure function is stable under perturbed same-shape inputs
    pure = lambda x: x * 2  # noqa: E731
    ta = lower_jit(pure, jnp.zeros(4)).as_text()
    tb = lower_jit(pure, jnp.full(4, 9.0)).as_text()
    assert check_signature_stable(ta, tb, entry="e") == []


def test_donation_policy_rule():
    class FakeRunner:
        _donate = jax.default_backend() != "cpu"

    assert check_runner_donation_policy(FakeRunner()) == []
    FakeRunner._donate = not FakeRunner._donate
    findings = check_runner_donation_policy(FakeRunner())
    assert len(findings) == 1
    assert findings[0].rule == "recompile.donation-policy"


def test_donation_aliasing_rule_fires_on_unaliased_leaf():
    """A donated pytree whose leaves do NOT all alias into the output
    (here: a donated arg the function drops entirely) must be flagged
    with the leaf path in the finding."""
    def drops_donated(dropped, kept):
        return {"out": kept * 2}

    donated = {"buf": jnp.zeros(128, jnp.uint32)}
    text = lower_jit(drops_donated, donated, jnp.ones(128),
                     donate_argnums=(0,)).compile().as_text()
    findings = check_donation_aliasing(text, donated, 0,
                                       entry="seeded.drops_donated")
    assert len(findings) == 1
    assert findings[0].rule == "recompile.donation-unaliased"
    assert "buf" in findings[0].primitive


# ---------------------------------------------------------------------------
# parity family
# ---------------------------------------------------------------------------

def test_parity_clean_on_real_tree():
    assert check_fused_parity() == []


def test_parity_extractors_see_real_sources():
    assert "ALU" in kernel_hot_opclasses()
    assert "SSECVT" in step_unsupported_opclasses()


def test_parity_fires_on_kernel_claim_mismatch():
    """Kernel hot_class grows an opclass the claim doesn't carry (or vice
    versa): parity.claim-vs-kernel with the opclass named."""
    pstep_src = "hot_class = ((opc == U.OPC_NOP) | (opc == U.OPC_PUSH))\n"
    step_src = ("unsupported = pre_live & (is_(U.OPC_IRET))\n"
                "x = is_(U.OPC_NOP)\n")
    findings = check_fused_parity(claimed={"NOP"}, pstep_src=pstep_src,
                                  step_src=step_src)
    assert [ (f.rule, f.primitive) for f in findings ] == [
        ("parity.claim-vs-kernel", "OPC_PUSH")]
    assert "pstep" in findings[0].entry


def test_parity_fires_on_unsupported_overlap():
    """A claimed in-kernel opclass appearing in step.py's oracle-diverting
    `unsupported` expression: the park/resume seam would diverge."""
    pstep_src = "hot_class = (opc == U.OPC_JCC)\n"
    step_src = "unsupported = pre_live & (is_(U.OPC_JCC))\n"
    findings = check_fused_parity(claimed={"JCC"}, pstep_src=pstep_src,
                                  step_src=step_src)
    assert ("parity.fused-vs-unsupported", "OPC_JCC") in [
        (f.rule, f.primitive) for f in findings]


def test_parity_resolves_intermediate_bindings():
    """The house style routes diverting predicates through locals
    (`movcr_bad`, `x87_oracle`) and sometimes `|=` — the rule must see
    through both, not just literal OPC names on the final RHS."""
    step_src = ("jcc_bad = is_(U.OPC_JCC) & weird_mode\n"
                "unsupported = pre_live & (is_(U.OPC_IRET) | jcc_bad)\n"
                "unsupported |= is_(U.OPC_MSR)\n")
    assert step_unsupported_opclasses(step_src) == {"JCC", "IRET", "MSR"}
    findings = check_fused_parity(claimed={"JCC"},
                                  pstep_src="hot_class = (opc == U.OPC_JCC)",
                                  step_src=step_src)
    assert ("parity.fused-vs-unsupported", "OPC_JCC") in [
        (f.rule, f.primitive) for f in findings]
    # the real tree resolves through its intermediates too
    assert {"MOVCR", "DIV", "X87"} <= step_unsupported_opclasses()


def test_parity_fires_on_missing_step_dispatch():
    pstep_src = "hot_class = (opc == U.OPC_MOV)\n"
    step_src = "unsupported = pre_live & (is_(U.OPC_IRET))\n"
    findings = check_fused_parity(claimed={"MOV"}, pstep_src=pstep_src,
                                  step_src=step_src)
    assert [(f.rule, f.primitive) for f in findings] == [
        ("parity.fused-vs-dispatch", "OPC_MOV")]


# ---------------------------------------------------------------------------
# telemetry report: compile events per executor shape + churn warning
# ---------------------------------------------------------------------------

def _report(path):
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
    import telemetry_report

    return telemetry_report.summarize(path)


def test_report_surfaces_compile_shapes_and_churn(tmp_path, capsys):
    """ISSUE 5 satellite: >1 compile for one executor shape is shape-churn
    and must surface as a warning, not stay buried in the JSONL."""
    events = [
        {"ts": 1.0, "seq": 0, "type": "run-start", "subcommand": "t"},
        {"ts": 1.1, "seq": 1, "type": "compile", "chunk_steps": 64,
         "donate": False},
        {"ts": 1.2, "seq": 2, "type": "compile", "chunk_steps": 1024,
         "donate": False},
        {"ts": 1.3, "seq": 3, "type": "compile", "chunk_steps": 64,
         "donate": False},
        {"ts": 2.0, "seq": 4, "type": "run-end", "metrics": {}},
    ]
    path = tmp_path / "events.jsonl"
    path.write_text("".join(json.dumps(e) + "\n" for e in events))
    summary = _report(path)
    assert summary["compiles"]["total"] == 3
    assert summary["compiles"]["by_shape"]["chunk_steps=64,donate=False"] == 2
    assert summary["compile_shape_churn"] == {
        "chunk_steps=64,donate=False": 2}

    import telemetry_report

    telemetry_report._print_human(summary)
    out = capsys.readouterr().out
    assert "shape-churn" in out and "compiled 2x" in out


def test_report_no_churn_for_distinct_shapes(tmp_path):
    events = [
        {"ts": 1.0, "seq": 0, "type": "run-start", "subcommand": "t"},
        {"ts": 1.1, "seq": 1, "type": "compile", "chunk_steps": 64,
         "donate": False},
        {"ts": 1.2, "seq": 2, "type": "compile", "kind": "pallas-fused",
         "k_steps": 32},
        {"ts": 2.0, "seq": 3, "type": "run-end", "metrics": {}},
    ]
    path = tmp_path / "events.jsonl"
    path.write_text("".join(json.dumps(e) + "\n" for e in events))
    summary = _report(path)
    assert summary["compiles"]["total"] == 2
    assert summary["compile_shape_churn"] == {}


# ---------------------------------------------------------------------------
# findings plumbing + full lint
# ---------------------------------------------------------------------------

def test_finding_formats_provenance():
    f = Finding(rule="budget.kernel-count", entry="xla_step",
                primitive="gather", message="over", count=90, budget=81)
    assert f.as_dict() == {"rule": "budget.kernel-count",
                           "entry": "xla_step", "primitive": "gather",
                           "message": "over", "count": 90, "budget": 81}
    s = str(f)
    assert "gather" in s and "90" in s and "81" in s


def test_lint_cli_parity_only_with_telemetry(tmp_path, capsys):
    """The CLI path end to end on the cheap family: clean exit, CLEAN
    line, and a well-formed events.jsonl (run-start / run-end)."""
    from wtf_tpu.analysis import main

    rc = main(["--families", "parity", "--telemetry-dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "CLEAN" in out

    from wtf_tpu.telemetry import read_events

    types = [r["type"] for r in read_events(tmp_path / "events.jsonl")]
    assert types[0] == "run-start" and types[-1] == "run-end"


def test_lint_unknown_family_rejected():
    with pytest.raises(ValueError, match="unknown lint families"):
        run_lint(families=["nonsense"])


def test_rebaseline_without_budget_family_rejected():
    """--rebaseline with a families filter that skips `budget` must fail
    loudly, not silently leave the pin stale."""
    with pytest.raises(ValueError, match="rebaseline"):
        run_lint(families=["parity"], rebaseline=True)


@pytest.mark.slow
def test_full_lint_clean_on_tree(tmp_path):
    """The acceptance gate: every family against the real tree —
    compiles the step ladder and the sharded mesh chunk (~minutes on
    the 1-core box), so slow tier; tier-1 covers dtype via test_limbs,
    parity/negative paths above, mesh via test_meshrun, and the
    contract families via test_flow."""
    from wtf_tpu.analysis.rules import FAMILIES
    from wtf_tpu.telemetry import Registry

    registry = Registry()
    findings, info = run_lint(registry=registry)
    assert findings == [], [str(f) for f in findings]
    assert info["kernel_counts"]["total"] == \
        load_budgets()["xla_step"]["total"]
    assert registry.dump().get("analysis.families_run") == len(FAMILIES)
