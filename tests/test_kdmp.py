"""kdmp parser tests: write_kdmp fixtures round-trip through both the
native C++ parser and the pure-Python fallback, and a .dmp-backed snapshot
actually fuzzes (VERDICT round-2 item 4's done criterion)."""

import struct

import pytest

from wtf_tpu.core.cpustate import CpuState
from wtf_tpu.snapshot import kdmp
from wtf_tpu.snapshot.loader import load_snapshot
from wtf_tpu.harness import demo_tlv


def _pages():
    # non-contiguous PFNs -> multiple runs / bitmap holes
    return {
        0x10: bytes([0x11]) * 0x1000,
        0x11: bytes([0x22]) * 0x1000,
        0x40: bytes([0x33]) * 0x1000,
        0x1000: bytes(range(256)) * 16,
    }


@pytest.mark.parametrize("dump_type", ["full", "bmp"])
def test_roundtrip_python(tmp_path, dump_type, monkeypatch):
    path = tmp_path / "mem.dmp"
    cpu = CpuState()
    cpu.rip = 0x1337
    cpu.rax = 0xAABBCCDD
    cpu.rflags = 0x246
    kdmp.write_kdmp(path, _pages(), dump_type=dump_type,
                    dtb=0x1AD000, cpu=cpu, bugcheck_code=0xDEADDEAD)
    # force the pure-python path
    monkeypatch.setattr(kdmp, "_parse_native", lambda p: None)
    info = kdmp.parse_kdmp_info(path)
    assert info.dtb == 0x1AD000
    assert info.bugcheck_code == 0xDEADDEAD
    assert info.n_pages == 4
    regs = info.context_registers()
    assert regs["rip"] == 0x1337
    assert regs["rax"] == 0xAABBCCDD
    assert regs["rflags"] == 0x246
    pages = kdmp.parse_kdmp(path)
    assert pages.keys() == _pages().keys()
    for pfn, data in _pages().items():
        assert pages[pfn] == data, hex(pfn)


@pytest.mark.parametrize("dump_type", ["full", "bmp"])
def test_roundtrip_native(tmp_path, dump_type):
    lib = kdmp._native_lib()
    if lib is None:
        pytest.skip("no native toolchain")
    path = tmp_path / "mem.dmp"
    kdmp.write_kdmp(path, _pages(), dump_type=dump_type, dtb=0x1AD000)
    info, index = kdmp._parse_native(path)
    assert info.dump_type == (1 if dump_type == "full" else 5)
    assert info.dtb == 0x1AD000
    assert {pfn for pfn, _ in index} == _pages().keys()
    pages = kdmp.parse_kdmp(path)
    for pfn, data in _pages().items():
        assert pages[pfn] == data, hex(pfn)


def test_native_and_python_agree(tmp_path):
    lib = kdmp._native_lib()
    if lib is None:
        pytest.skip("no native toolchain")
    path = tmp_path / "mem.dmp"
    kdmp.write_kdmp(path, _pages(), dump_type="bmp", dtb=0x7777000)
    native_info, native_index = kdmp._parse_native(path)
    with open(path, "rb") as f:
        py_info, py_index = kdmp._parse_python(f.read())
    assert native_index == py_index
    assert native_info.dtb == py_info.dtb
    assert native_info.context_raw == py_info.context_raw


def test_bad_signature(tmp_path):
    path = tmp_path / "mem.dmp"
    path.write_bytes(b"NOPE" * 0x1000)
    with pytest.raises(kdmp.KdmpError):
        kdmp.parse_kdmp(path)


def test_kernel_dump_rejected(tmp_path):
    path = tmp_path / "mem.dmp"
    header = bytearray(0x3000)
    struct.pack_into("<II", header, 0, kdmp.SIG_PAGE, kdmp.SIG_DU64)
    struct.pack_into("<I", header, 0xF98, kdmp.KERNEL_DUMP)
    path.write_bytes(bytes(header))
    with pytest.raises(kdmp.KdmpError, match="partial kernel"):
        kdmp.parse_kdmp(path)


def test_dmp_snapshot_fuzzes(tmp_path):
    """A demo_tlv snapshot exported as mem.dmp + regs.json loads through
    load_snapshot and reproduces the planted crash end-to-end."""
    from wtf_tpu.backend import create_backend
    from wtf_tpu.core.results import Crash, Ok
    from wtf_tpu.snapshot.loader import dump_cpu_state_json

    import numpy as np

    snap = demo_tlv.build_snapshot()
    state = tmp_path / "state"
    state.mkdir()
    # export guest memory as a BMP crash dump
    table = np.asarray(snap.physmem.image.frame_table)
    page_data = np.asarray(snap.physmem.image.pages)
    pages = {int(pfn): bytes(page_data[int(table[pfn])].tobytes())
             for pfn in np.nonzero(table)[0]}
    kdmp.write_kdmp(state / "mem.dmp", pages, dump_type="bmp",
                    dtb=snap.cpu.cr3, cpu=snap.cpu)
    (state / "regs.json").write_text(dump_cpu_state_json(snap.cpu))

    loaded = load_snapshot(state)
    assert loaded.cpu.rip == snap.cpu.rip
    backend = create_backend("emu", loaded)
    backend.initialize()
    demo_tlv.TARGET.init(backend)
    results = backend.run_batch(
        [b"\x01\x02AB", bytes([3, 64]) + b"A" * 64], demo_tlv.TARGET)
    assert isinstance(results[0], Ok)
    assert isinstance(results[1], Crash)
