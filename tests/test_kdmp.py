"""kdmp parser tests: write_kdmp fixtures round-trip through both the
native C++ parser and the pure-Python fallback, and a .dmp-backed snapshot
actually fuzzes (VERDICT round-2 item 4's done criterion)."""

import struct

import pytest

from wtf_tpu.core.cpustate import CpuState
from wtf_tpu.snapshot import kdmp
from wtf_tpu.snapshot.loader import load_snapshot
from wtf_tpu.harness import demo_tlv


def _pages():
    # non-contiguous PFNs -> multiple runs / bitmap holes
    return {
        0x10: bytes([0x11]) * 0x1000,
        0x11: bytes([0x22]) * 0x1000,
        0x40: bytes([0x33]) * 0x1000,
        0x1000: bytes(range(256)) * 16,
    }


@pytest.mark.parametrize("dump_type", ["full", "bmp"])
def test_roundtrip_python(tmp_path, dump_type, monkeypatch):
    path = tmp_path / "mem.dmp"
    cpu = CpuState()
    cpu.rip = 0x1337
    cpu.rax = 0xAABBCCDD
    cpu.rflags = 0x246
    kdmp.write_kdmp(path, _pages(), dump_type=dump_type,
                    dtb=0x1AD000, cpu=cpu, bugcheck_code=0xDEADDEAD)
    # force the pure-python path
    monkeypatch.setattr(kdmp, "_parse_native", lambda p: None)
    info = kdmp.parse_kdmp_info(path)
    assert info.dtb == 0x1AD000
    assert info.bugcheck_code == 0xDEADDEAD
    assert info.n_pages == 4
    regs = info.context_registers()
    assert regs["rip"] == 0x1337
    assert regs["rax"] == 0xAABBCCDD
    assert regs["rflags"] == 0x246
    pages = kdmp.parse_kdmp(path)
    assert pages.keys() == _pages().keys()
    for pfn, data in _pages().items():
        assert pages[pfn] == data, hex(pfn)


@pytest.mark.parametrize("dump_type", ["full", "bmp"])
def test_roundtrip_native(tmp_path, dump_type):
    lib = kdmp._native_lib()
    if lib is None:
        pytest.skip("no native toolchain")
    path = tmp_path / "mem.dmp"
    kdmp.write_kdmp(path, _pages(), dump_type=dump_type, dtb=0x1AD000)
    info, index = kdmp._parse_native(path)
    assert info.dump_type == (1 if dump_type == "full" else 5)
    assert info.dtb == 0x1AD000
    assert {pfn for pfn, _ in index} == _pages().keys()
    pages = kdmp.parse_kdmp(path)
    for pfn, data in _pages().items():
        assert pages[pfn] == data, hex(pfn)


def test_native_and_python_agree(tmp_path):
    lib = kdmp._native_lib()
    if lib is None:
        pytest.skip("no native toolchain")
    path = tmp_path / "mem.dmp"
    kdmp.write_kdmp(path, _pages(), dump_type="bmp", dtb=0x7777000)
    native_info, native_index = kdmp._parse_native(path)
    with open(path, "rb") as f:
        py_info, py_index = kdmp._parse_python(f.read())
    assert native_index == py_index
    assert native_info.dtb == py_info.dtb
    assert native_info.context_raw == py_info.context_raw


def test_bad_signature(tmp_path):
    path = tmp_path / "mem.dmp"
    path.write_bytes(b"NOPE" * 0x1000)
    with pytest.raises(kdmp.KdmpError):
        kdmp.parse_kdmp(path)


def test_kernel_dump_rejected(tmp_path):
    path = tmp_path / "mem.dmp"
    header = bytearray(0x3000)
    struct.pack_into("<II", header, 0, kdmp.SIG_PAGE, kdmp.SIG_DU64)
    struct.pack_into("<I", header, 0xF98, kdmp.KERNEL_DUMP)
    path.write_bytes(bytes(header))
    with pytest.raises(kdmp.KdmpError, match="partial kernel"):
        kdmp.parse_kdmp(path)


def test_dmp_snapshot_fuzzes(tmp_path):
    """A demo_tlv snapshot exported as mem.dmp + regs.json loads through
    load_snapshot and reproduces the planted crash end-to-end."""
    from wtf_tpu.backend import create_backend
    from wtf_tpu.core.results import Crash, Ok
    from wtf_tpu.snapshot.loader import dump_cpu_state_json

    import numpy as np

    snap = demo_tlv.build_snapshot()
    state = tmp_path / "state"
    state.mkdir()
    # export guest memory as a BMP crash dump
    table = np.asarray(snap.physmem.image.frame_table)[0]
    page_data = np.asarray(snap.physmem.image.pages)
    pages = {int(pfn): bytes(page_data[int(table[pfn])].tobytes())
             for pfn in np.nonzero(table)[0]}
    kdmp.write_kdmp(state / "mem.dmp", pages, dump_type="bmp",
                    dtb=snap.cpu.cr3, cpu=snap.cpu)
    (state / "regs.json").write_text(dump_cpu_state_json(snap.cpu))

    loaded = load_snapshot(state)
    assert loaded.cpu.rip == snap.cpu.rip
    backend = create_backend("emu", loaded)
    backend.initialize()
    demo_tlv.TARGET.init(backend)
    results = backend.run_batch(
        [b"\x01\x02AB", bytes([3, 64]) + b"A" * 64], demo_tlv.TARGET)
    assert isinstance(results[0], Ok)
    assert isinstance(results[1], Crash)


# ---------------------------------------------------------------------------
# differential vs the REFERENCE kdmp-parser (VERDICT r3 item 4)
# ---------------------------------------------------------------------------

_REF_LIB = "/root/reference/src/libs/kdmp-parser/src/lib"


@pytest.fixture(scope="session")
def ref_testapp(tmp_path_factory):
    """Compile the reference header-only parser into a check binary (our
    tests/native/kdmp_ref_check.cc); skip where the reference tree or a
    C++ toolchain isn't available."""
    import shutil
    import subprocess
    from pathlib import Path as _P

    if not _P(_REF_LIB).is_dir():
        pytest.skip("reference kdmp-parser sources not available")
    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    src = _P(__file__).parent / "native" / "kdmp_ref_check.cc"
    out = tmp_path_factory.mktemp("kdmpref") / "kdmp_ref_check"
    proc = subprocess.run(
        ["g++", "-O1", "-std=c++20", f"-I{_REF_LIB}", str(src),
         "-o", str(out)], capture_output=True, text=True)
    if proc.returncode != 0:
        pytest.skip(f"reference parser does not build: {proc.stderr[-300:]}")
    return out


def _fnv1a_pages(pages):
    h = 0xCBF29CE484222325
    for pfn in sorted(pages):
        pa = pfn << 12
        for chunk in (pa.to_bytes(8, "little"), pages[pfn]):
            for b in chunk:
                h = ((h ^ b) * 0x100000001B3) & (1 << 64) - 1
    return h


@pytest.mark.parametrize("dump_type", ["full", "bmp"])
def test_differential_vs_reference_parser(tmp_path, dump_type, ref_testapp):
    """Break the closed writer->parser loop: the same dump must yield the
    same DTB / context / page set / page CONTENTS from the reference's
    battle-tested parser and from ours (native + pure-Python).  A shared
    misreading of the format between our writer and our parser would
    round-trip cleanly but diverge here."""
    import json
    import subprocess

    path = tmp_path / "mem.dmp"
    cpu = CpuState()
    cpu.rip = 0xFFFFF805_1087_76A0
    cpu.rsp = 0xFFFFF805_1356_84F8
    cpu.rax = 3
    cpu.rcx = 1
    cpu.r15 = 0x52
    cpu.rflags = 0x40202
    cpu.cs.selector = 0x10
    cpu.ss.selector = 0x18
    pages = _pages()
    kdmp.write_kdmp(path, pages, dump_type=dump_type, dtb=0x6D4000, cpu=cpu)

    proc = subprocess.run([str(ref_testapp), str(path)],
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    ref = json.loads(proc.stdout)

    # reference enum: FullDump=1, BMPDump=5 (kdmp-parser-structs.h)
    assert ref["type"] == {"full": 1, "bmp": 5}[dump_type]
    assert ref["dtb"] == 0x6D4000
    assert ref["n_pages"] == len(pages)
    assert ref["rip"] == cpu.rip
    assert ref["rsp"] == cpu.rsp
    assert ref["rax"] == cpu.rax
    assert ref["rcx"] == cpu.rcx
    assert ref["r15"] == cpu.r15
    assert ref["eflags"] == cpu.rflags
    assert ref["seg_cs"] == 0x10 and ref["seg_ss"] == 0x18
    assert ref["first_pa"] == min(pages) << 12
    assert ref["last_pa"] == max(pages) << 12

    # now OUR parsers (both paths) must agree with the reference, page
    # contents included (same fnv1a(pa || bytes) digest formula)
    from unittest import mock

    for parser in ("native", "python"):
        if parser == "native" and kdmp._native_lib() is None:
            continue
        patch = (mock.patch.object(kdmp, "_parse_native", lambda p: None)
                 if parser == "python" else mock.patch.object(
                     kdmp, "_IGNORED_", None, create=True))
        with patch:
            info = kdmp.parse_kdmp_info(path)
            got_pages = kdmp.parse_kdmp(path)
        regs = info.context_registers()
        assert info.dtb == ref["dtb"], parser
        assert info.n_pages == ref["n_pages"], parser
        assert regs["rip"] == ref["rip"], parser
        assert regs["rsp"] == ref["rsp"], parser
        assert regs["rflags"] == ref["eflags"], parser
        assert regs["cs"] == ref["seg_cs"], parser
        assert _fnv1a_pages(got_pages) == ref["pages_digest"], parser
