"""Guest exception delivery through the IDT (VERDICT round-3 item 2).

Done criteria being proven here:
  - a user-mode guest with a guard-page stack GROWS it through #PF ->
    kernel handler -> iretq instead of false-crashing (both executors),
  - an unhandled fault round-trips kernel->user into the
    RtlDispatchException-analog where the crash-detection hook parses the
    kernel-built EXCEPTION_RECORD (SEH dispatch),
  - page_faults_memory_if_needed actually injects a #PF the guest
    services, with the reference's probe-inject-retry dance
    (bochscpu_backend.cc:917-999).
"""

import shutil

import pytest

from wtf_tpu.backend import create_backend
from wtf_tpu.core.results import Crash, Ok
from wtf_tpu.harness import demo_usermode as du

GROW4 = b"\x01\x04"          # touch 4 guard pages below rsp
WILD_READ = b"\x02"          # read unmapped 0xDEAD0000
DIV_ZERO = b"\x03"           # #DE via IDT gate 0
DIV_RIP = du.USER_CODE + 97  # the `div ecx` instruction


def make_backend(name, **kw):
    backend = create_backend(name, du.build_snapshot(), limit=100_000, **kw)
    backend.initialize()
    du.TARGET.init(backend)
    return backend


@pytest.mark.parametrize("backend_name", ["emu", "tpu"])
def test_guard_page_stack_grows(backend_name):
    backend = make_backend(
        backend_name, **({"n_lanes": 2} if backend_name == "tpu" else {}))
    results = backend.run_batch([GROW4], du.TARGET)
    assert isinstance(results[0], Ok), results[0]
    # the grown pages are real memory now: the loop stored its countdown
    # counter into each freshly mapped page (page n holds N+1-n)
    rsp0 = du.STACK_TOP - 0x10
    for n in range(1, 5):
        got = int.from_bytes(backend.virt_read(rsp0 - n * 0x1000, 8),
                             "little")
        assert got == 5 - n, f"page {n}: {got}"


@pytest.mark.parametrize("backend_name", ["emu", "tpu"])
def test_seh_dispatch_names_the_crash(backend_name):
    """kernel -> user exception round trip: the hook at the
    RtlDispatchException analog parses the EXCEPTION_RECORD the guest
    kernel built and refines the A/V (crash_detection_umode.cc:53-129)."""
    backend = make_backend(
        backend_name, **({"n_lanes": 4} if backend_name == "tpu" else {}))
    results = backend.run_batch([WILD_READ, DIV_ZERO, b"", GROW4], du.TARGET)
    assert results[0].name == "crash-read-0xdead0000"
    assert results[1].name == f"crash-divide-by-zero-{DIV_RIP:#x}"
    assert isinstance(results[2], Ok)
    assert isinstance(results[3], Ok)


@pytest.mark.parametrize("backend_name", ["emu", "tpu"])
def test_stack_grows_through_faulting_push(backend_name):
    """Stacks in real programs grow via PUSH/CALL, where the faulting
    micro-op is the store of the instruction itself: the retry after
    delivery must re-run it with rsp NOT yet decremented (a partial-state
    bug here skews rsp by 8 per grown page)."""
    backend = make_backend(
        backend_name, **({"n_lanes": 2} if backend_name == "tpu" else {}))
    results = backend.run_batch([b"\x04\x03"], du.TARGET)
    assert isinstance(results[0], Ok), results[0]
    rsp0 = du.STACK_TOP - 0x10
    assert backend.get_reg(4) == rsp0 - 3 * 0x1000  # exact final rsp
    for k in range(1, 4):
        got = int.from_bytes(backend.virt_read(rsp0 - k * 0x1000, 8),
                             "little")
        assert got == 4 - k, f"push {k}: {got}"


NONCANON = b"\x05"          # read 0x800000000000 -> #GP via gate 13


@pytest.mark.parametrize("backend_name", ["emu", "tpu"])
def test_noncanonical_is_gp_not_pf(backend_name):
    """Non-canonical accesses vector through #GP (gate 13), not #PF —
    and surface as an A/V with NO faulting address, exactly like
    KiGeneralProtectionFault."""
    backend = make_backend(
        backend_name, **({"n_lanes": 2} if backend_name == "tpu" else {}))
    results = backend.run_batch([NONCANON], du.TARGET)
    assert results[0].name == "crash-read-0x0", results[0]


def test_backends_agree_and_device_stays_native():
    cases = [GROW4, WILD_READ, DIV_ZERO, b"", b"\x01\x0e", b"\x01\x00",
             b"\x04\x05", NONCANON]
    emu = make_backend("emu")
    tpu = make_backend("tpu", n_lanes=8)
    r_emu = emu.run_batch(cases, du.TARGET)
    r_tpu = tpu.run_batch(cases, du.TARGET)
    for i, (a, b) in enumerate(zip(r_emu, r_tpu)):
        assert type(a) is type(b), f"case {i}: emu={a} tpu={b}"
        if isinstance(a, Crash):
            assert a.name == b.name, f"case {i}: emu={a} tpu={b}"
    # delivery happened host-side; everything else ran on device (the only
    # oracle fallbacks allowed are the iretq returns: 2 per delivery)
    assert tpu.runner.stats["exceptions_delivered"] > 0
    assert (tpu.runner.stats["fallbacks"]
            <= 2 * tpu.runner.stats["exceptions_delivered"])


@pytest.mark.parametrize("backend_name", ["emu", "tpu"])
def test_restore_undoes_the_growth(backend_name):
    backend = make_backend(
        backend_name, **({"n_lanes": 2} if backend_name == "tpu" else {}))
    results = backend.run_batch([GROW4], du.TARGET)
    assert isinstance(results[0], Ok)
    backend.restore()
    with pytest.raises(Exception):
        backend.virt_translate(du.STACK_TOP - 0x2000)  # guard again


@pytest.mark.parametrize("backend_name", ["emu", "tpu"])
def test_page_faults_memory_if_needed_injects(backend_name):
    """The reference dance (bochscpu_backend.cc:917-999): the breakpoint
    handler probes, injects a #PF, returns; the guest pages the memory in
    and retries the instruction; the breakpoint re-fires; now the range is
    mapped and the host write proceeds."""
    backend = make_backend(
        backend_name, **({"n_lanes": 2} if backend_name == "tpu" else {}))
    target_gva = du.STACK_TOP - 0x3000   # two pages into the guard
    fires = []

    def on_entry(b):
        fires.append(b.rip())
        if b.page_faults_memory_if_needed(target_gva, 8):
            return  # guest will service the fault; we re-fire
        b.virt_write(target_gva, b"paged-in")
        b.rip(du.FINISH_GVA)

    backend.set_breakpoint(du.USER_CODE, on_entry)
    results = backend.run_batch([b""], du.TARGET)
    assert isinstance(results[0], Ok)
    assert len(fires) == 2, fires       # probe+inject, then write
    assert backend.virt_read(target_gva, 8) == b"paged-in"


@pytest.mark.skipif(shutil.which("as") is None, reason="binutils missing")
def test_embedded_hex_matches_sources():
    """The embedded bytes must stay in sync with the _ASM sources."""
    from asmhelper import assemble

    # strip the label-offset comments the module keeps for humans
    def clean(src):
        return "\n".join(line.split("#")[0] for line in src.splitlines())

    assert assemble(clean(du._USER_ASM)) == du._USER_CODE
    assert assemble(clean(du._KERN_ASM)) == du._KERN_CODE


def test_delivery_soak_random_campaign():
    """A short mangle campaign over the delivery-heavy target: thousands
    of random inputs interleave stack growth, SEH/GP/DE dispatch, and
    restores across lanes.  No lane may end HARD_ERROR (a delivery-loop
    bug) and every crash must carry a dispatcher-named class."""
    import random

    from wtf_tpu.core.results import StatusCode
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.loop import FuzzLoop
    from wtf_tpu.fuzz.mutator import ByteMutator

    rng = random.Random(0x5EED5)
    backend = make_backend("tpu", n_lanes=32)
    corpus = Corpus(rng=rng)
    for cmd in (1, 2, 3, 4, 5):
        corpus.add(bytes([cmd, 3]))
    loop = FuzzLoop(backend, du.TARGET, ByteMutator(rng, 16), corpus)
    stats = loop.fuzz(runs=2000)
    assert stats.testcases >= 2000
    assert backend.runner.stats["exceptions_delivered"] > 100
    # no lane ever parked HARD_ERROR (lane_errors holds only soft notes
    # like double-fault downgrades, never servicing failures)
    statuses = backend.runner.statuses()
    assert int((statuses == int(StatusCode.HARD_ERROR)).sum()) == 0
    for name in loop.crash_names:
        assert name.startswith(("crash-read-", "crash-write-",
                                "crash-execute-", "crash-divide-by-zero-",
                                "crash-av", "crash-int-")), name


def test_traced_run_through_delivery(tmp_path):
    """A rip trace of a testcase that takes a #PF -> kernel handler ->
    iretq round trip must contain user, kernel, and post-retry rips in
    order (tracing delegates to the oracle, which delivers too)."""
    backend = make_backend("tpu", n_lanes=2)
    du.TARGET.insert_testcase(backend, GROW4)
    path = tmp_path / "t.rip"
    backend.set_trace_file(path, "rip")
    result = backend.run()
    assert isinstance(result, Ok), result
    rips = [int(x, 16) for x in path.read_text().split()]
    kern = [r for r in rips if r >= du.KERN_CODE]
    user = [r for r in rips if r < du.KERN_CODE]
    assert kern and user
    assert rips[0] == du.USER_CODE
    # the handler ran BETWEEN user rips (fault -> kernel -> retry)
    first_kern = rips.index(kern[0])
    assert any(r < du.KERN_CODE for r in rips[first_kern + 1:])
