"""Deep-execution target + adaptive chunk growth tests (the BASELINE
config-5 shape: very long executions per testcase)."""

import struct

import numpy as np
import pytest

from wtf_tpu.backend import create_backend
from wtf_tpu.core.results import Ok, Timedout
from wtf_tpu.harness import demo_spin as ds


def make_backend(name, **kw):
    backend = create_backend(name, ds.build_snapshot(), **kw)
    backend.initialize()
    ds.TARGET.init(backend)
    return backend


def spin(k):
    return struct.pack("<I", k)


def test_spin_depth_scales_with_input():
    backend = make_backend("emu")
    for k in (0, 10, 500):
        results = backend.run_batch([spin(k)], ds.TARGET)
        assert isinstance(results[0], Ok)
        got = backend.cpu.icount
        assert got == pytest.approx(k * ds.INSNS_PER_ITER, abs=16), (k, got)
        ds.TARGET.restore()
        backend.restore()


def test_adaptive_chunks_reduce_round_trips():
    """Same results, far fewer host<->device round trips once the decode
    cache warms up (the deep-execution throughput lever)."""
    results = {}
    for adaptive in (False, True):
        backend = make_backend("tpu", n_lanes=4, chunk_steps=64)
        backend.runner.adaptive_chunks = adaptive
        # cap growth at 1024 steps: proves the adaptive win without paying
        # the 16384-step chunk's XLA compile in CI (growth to 65536 is the
        # same code path, exercised by campaigns)
        backend.runner._chunk_sizes = [64, 1024]
        res = backend.run_batch([spin(3000)] * 4, ds.TARGET)
        assert all(isinstance(r, Ok) for r in res)
        results[adaptive] = (
            int(np.asarray(backend.runner.machine.icount).sum()),
            backend.runner.stats["chunks"],
        )
    instr_fixed, chunks_fixed = results[False]
    instr_adaptive, chunks_adaptive = results[True]
    assert instr_fixed == instr_adaptive  # bit-identical execution
    assert chunks_adaptive < chunks_fixed / 5


def test_deep_timeout_is_instruction_precise():
    """The limit check runs per device step, so TIMEDOUT lands on the
    exact instruction budget even inside a 16k-step chunk."""
    limit = 5000
    backend = make_backend("tpu", n_lanes=2, chunk_steps=64, limit=limit)
    res = backend.run_batch([spin(1 << 24), spin(3)], ds.TARGET)
    assert isinstance(res[0], Timedout)
    assert isinstance(res[1], Ok)
    icount = np.asarray(backend.runner.machine.icount)
    assert int(icount[0]) == limit


def test_chunk_ladder_reaches_cap():
    """The adaptive-chunk ladder's top rung must reach 65536 for any base
    (a short ladder costs deep executions 8x the host round trips)."""
    for base in (8, 64, 256, 512, 4096):
        backend = make_backend("tpu", n_lanes=2, chunk_steps=base)
        assert backend.runner._chunk_sizes[-1] == 1 << 16, (
            base, backend.runner._chunk_sizes)
