"""Deep-execution target + adaptive chunk growth tests (the BASELINE
config-5 shape: very long executions per testcase)."""

import struct

import numpy as np
import pytest

from wtf_tpu.backend import create_backend
from wtf_tpu.core.results import Ok, Timedout
from wtf_tpu.harness import demo_spin as ds


def make_backend(name, **kw):
    backend = create_backend(name, ds.build_snapshot(), **kw)
    backend.initialize()
    ds.TARGET.init(backend)
    return backend


def spin(k):
    return struct.pack("<I", k)


def test_spin_depth_scales_with_input():
    backend = make_backend("emu")
    for k in (0, 10, 500):
        results = backend.run_batch([spin(k)], ds.TARGET)
        assert isinstance(results[0], Ok)
        got = backend.cpu.icount
        assert got == pytest.approx(k * ds.INSNS_PER_ITER, abs=16), (k, got)
        ds.TARGET.restore()
        backend.restore()


def test_adaptive_chunks_reduce_round_trips():
    """Same results, far fewer host<->device round trips once the decode
    cache warms up (the deep-execution throughput lever)."""
    results = {}
    for adaptive in (False, True):
        backend = make_backend("tpu", n_lanes=4, chunk_steps=64)
        backend.runner.adaptive_chunks = adaptive
        # cap growth at 1024 steps: proves the adaptive win without paying
        # the 16384-step chunk's XLA compile in CI (growth to 65536 is the
        # same code path, exercised by campaigns)
        backend.runner._chunk_sizes = [64, 1024]
        res = backend.run_batch([spin(3000)] * 4, ds.TARGET)
        assert all(isinstance(r, Ok) for r in res)
        results[adaptive] = (
            int(np.asarray(backend.runner.machine.icount).sum()),
            backend.runner.stats["chunks"],
        )
    instr_fixed, chunks_fixed = results[False]
    instr_adaptive, chunks_adaptive = results[True]
    assert instr_fixed == instr_adaptive  # bit-identical execution
    assert chunks_adaptive < chunks_fixed / 5


def test_deep_timeout_is_instruction_precise():
    """The limit check runs per device step, so TIMEDOUT lands on the
    exact instruction budget even inside a 16k-step chunk."""
    limit = 5000
    backend = make_backend("tpu", n_lanes=2, chunk_steps=64, limit=limit)
    res = backend.run_batch([spin(1 << 24), spin(3)], ds.TARGET)
    assert isinstance(res[0], Timedout)
    assert isinstance(res[1], Ok)
    icount = np.asarray(backend.runner.machine.icount)
    assert int(icount[0]) == limit


def test_chunk_ladder_reaches_cap():
    """The adaptive-chunk ladder's top rung must reach 65536 for any base
    (a short ladder costs deep executions 8x the host round trips)."""
    for base in (8, 64, 256, 512, 4096):
        backend = make_backend("tpu", n_lanes=2, chunk_steps=base)
        assert backend.runner._chunk_sizes[-1] == 1 << 16, (
            base, backend.runner._chunk_sizes)


def test_one_oracle_lane_does_not_stall_the_ladder():
    """VERDICT r4 item 4: a single lane looping through oracle-class
    instructions (fxsave here — the x87 state movers are the remaining
    oracle-serviced class) must not pin the whole batch to fine-grained
    chunks.  Chronic-lane servicing keeps the ladder growing and the lane
    rides the oracle burst; only broad events (decode misses, SMC,
    breakpoints) reset chunk size."""
    import sys
    sys.path.insert(0, "tests")
    from emurunner import DATA_BASE
    from test_step import make_runner
    from wtf_tpu.core.results import StatusCode

    n_iters = 3000
    asm = f"""
        test rax, rax
        jz oracle_path
        mov ecx, {n_iters}
    int_loop:
        dec ecx
        jnz int_loop
        int3
    oracle_path:
        mov rbx, {DATA_BASE}
        fld qword ptr [rbx]
        mov ecx, 30
    oracle_loop:
        fxsave [rbx+0x200]
        fxsave [rbx+0x400]
        dec ecx
        jnz oracle_loop
        fstp qword ptr [rbx+8]
        int3
    """
    data = {DATA_BASE: struct.pack("<d", 2.5).ljust(0x1000, b"\x00")}
    runner = make_runner(asm, data=data, n_lanes=4)
    runner._chunk_sizes = [64, 1024]  # CI-sized ladder (same code path)
    runner.burst_any_tier = True      # exercise the full burst in CI
    view = runner.view()
    for lane in range(1, 4):
        view.set_reg(lane, 0, 1)  # integer path; lane 0 stays on x87
    runner.push(view)
    status = runner.run()
    assert all(StatusCode(int(s)) == StatusCode.CRASH for s in status), (
        status, runner.lane_errors)
    # the x87 lane really went through the oracle, repeatedly
    assert runner.stats["fallbacks"] >= 60
    # the mechanism under test: servicing a single chronic lane no longer
    # resets the ladder, so the batch still reached the top rung...
    assert runner.stats["max_chunk_steps"] == 1024, runner.stats
    # ...and the chronic lane ran ahead on the oracle once its streak grew
    assert runner.stats["fallback_burst_steps"] > 0, runner.stats
    # memory result of the x87 lane is intact (oracle writes made it back)
    out = struct.unpack("<d", runner.view().virt_read(0, DATA_BASE + 8, 8))[0]
    assert out == 2.5

    # coverage parity: burst-stepped rips must report the same coverage a
    # one-dispatch-per-instruction servicing loop records (the burst owes
    # those bits via Runner._pending_cov; losing them would blind the
    # fuzzer to oracle-class regions)
    def covered(r, lane):
        words = np.asarray(r.machine.cov)[lane]
        return set(r.cache.rips_of_bits(words))

    burst_cov = covered(runner, 0)
    from wtf_tpu.interp.runner import Runner

    slow = make_runner(asm, data=data, n_lanes=4)
    slow._chunk_sizes = [64, 1024]
    orig_burst = Runner._fallback_burst
    Runner._fallback_burst = Runner._fallback_step  # disable run-ahead
    try:
        view2 = slow.view()
        for lane in range(1, 4):
            view2.set_reg(lane, 0, 1)
        slow.push(view2)
        slow.run()
    finally:
        Runner._fallback_burst = orig_burst
    assert covered(slow, 0) == burst_cov
    # edge-bitmap parity too: burst-stepped branches owe their edge-hash
    # bits (_pending_edge) — lane 0 ran through the same control flow
    assert np.array_equal(np.asarray(runner.machine.edge)[0],
                          np.asarray(slow.machine.edge)[0])


def test_burst_any_tier_cpu_override_not_forced():
    """ISSUE 4 satellite (VERDICT weak item 4): the any-instruction burst
    tier is a CONSTRUCTOR config now, not a hard-wired platform check —
    `Runner(..., burst_any_tier=True)` enables it on the CPU platform
    without poking runner attributes, and the override rides the backend
    kwargs path (`create_backend("tpu", ..., burst_any_tier=...)`).
    With the tier on, a chronic oracle lane runs ahead THROUGH device-class
    glue between its fxsave ops (more burst steps, fewer chunk dispatches);
    both ways execute bit-identically."""
    from tests.emurunner import DATA_BASE, build_guest
    from wtf_tpu.core.results import StatusCode
    from wtf_tpu.interp.runner import Runner
    from wtf_tpu.snapshot.loader import Snapshot

    asm = f"""
        mov rbx, {DATA_BASE}
        mov ecx, 12
    lp:
        fxsave [rbx+0x200]
        inc rax
        inc rdx
        fxsave [rbx+0x400]
        dec ecx
        jnz lp
        int3
    """
    data = {DATA_BASE: bytes(0x1000)}

    def run_with(tier):
        physmem, cpu, _ = build_guest(asm, data)
        runner = Runner(Snapshot(physmem=physmem, cpu=cpu), n_lanes=2,
                        chunk_steps=64, burst_any_tier=tier)
        assert runner.burst_any_tier is tier  # not the cpu-platform default
        status = runner.run()
        assert all(StatusCode(int(s)) == StatusCode.CRASH for s in status)
        return runner

    on = run_with(True)
    off = run_with(False)
    # identical execution either way
    assert np.array_equal(np.asarray(on.machine.gpr),
                          np.asarray(off.machine.gpr))
    assert np.array_equal(np.asarray(on.machine.icount),
                          np.asarray(off.machine.icount))
    assert np.array_equal(np.asarray(on.machine.cov),
                          np.asarray(off.machine.cov))
    # the tier actually engaged: the chronic lane ran ahead through the
    # inc/inc/dec/jnz glue on the oracle instead of bouncing back to the
    # device at every one
    assert (on.stats["fallback_burst_steps"]
            > off.stats["fallback_burst_steps"]), (
        on.stats["fallback_burst_steps"], off.stats["fallback_burst_steps"])
    assert on.stats["chunks"] < off.stats["chunks"]

    # the backend kwargs path carries the override too
    backend = make_backend("tpu", n_lanes=2, burst_any_tier=True)
    assert backend.runner.burst_any_tier is True
