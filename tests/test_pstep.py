"""Interpret-mode differential tests for the fused Pallas step kernel
(wtf_tpu/interp/pstep.py) and its park-and-resume ladder.

The fused fast path must be INVISIBLE except for speed: every test here
runs the same guest through the XLA-only ladder and the fused ladder
(`fused_step="on"`, kernel under pallas interpret mode on the CPU
platform) and requires bit-exact agreement on the complete machine state —
registers, rflags, rip, icount, statuses, coverage and edge bitmaps, and
dirty memory — plus oracle agreement where the EmuCpu reference applies.
The randomized grids sweep every hot-subset opclass; the seam tests pin
that a lane parked mid-chunk resumes on the XLA path with identical final
state, and that occupancy accounting (CTR_FUSED) is exact.
"""

import random

import numpy as np
import pytest

from tests.emurunner import DATA_BASE, build_guest, run_emu
from wtf_tpu.core.results import StatusCode
from wtf_tpu.interp.machine import (
    CTR_FUSED, CTR_INSTR, CTR_PARK_MEM, CTR_PARK_SUBSET,
)
from wtf_tpu.interp.runner import Runner
from wtf_tpu.snapshot.loader import Snapshot

# skip-with-reason guard: some jax builds ship without pallas (or without
# a working interpret mode); the suite must stay green there
pstep = pytest.importorskip("wtf_tpu.interp.pstep")
if not pstep.fused_available():
    pytest.skip("this jax build cannot run pallas interpret kernels",
                allow_module_level=True)

RF_CMP = 0x8D5 | 0x400  # same modeled-flags mask as tests/test_step.py

STATE_FIELDS = ("gpr", "rip", "rflags", "icount", "cov", "edge",
                "bp_skip", "ctr")


def _make_runner(asm, data=None, regs=None, n_lanes=2, limit=0, **kw):
    physmem, cpustate, _ = build_guest(asm, data)
    if regs:
        for name, value in regs.items():
            setattr(cpustate, name, value)
    snap = Snapshot(physmem=physmem, cpu=cpustate)
    runner = Runner(snap, n_lanes=n_lanes, chunk_steps=64, **kw)
    runner.limit = limit
    return runner


def _run_pair(asm, data=None, regs=None, n_lanes=2, limit=0, **kw):
    """The same guest through the XLA-only and the fused ladder."""
    out = []
    for mode in ("off", "on"):
        r = _make_runner(asm, data, regs, n_lanes, limit,
                         fused_step=mode, **kw)
        status = r.run()
        out.append((r, status))
    return out


def _assert_ladders_equal(r0, s0, r1, s1, check_mem=False):
    assert np.array_equal(s0, s1), (
        [StatusCode(int(x)).name for x in s0],
        [StatusCode(int(x)).name for x in s1])
    for field in STATE_FIELDS:
        a = np.asarray(getattr(r0.machine, field))
        b = np.asarray(getattr(r1.machine, field))
        if field == "ctr":
            # the fused-only counters (kernel occupancy + park split)
            # legitimately differ (that's the point); every other device
            # counter must agree exactly
            fused_only = [CTR_FUSED, CTR_PARK_SUBSET, CTR_PARK_MEM]
            a = np.delete(a, fused_only, axis=1)
            b = np.delete(b, fused_only, axis=1)
        assert np.array_equal(a, b), f"{field} diverged under fused ladder"
    if check_mem:
        v0, v1 = r0.view(), r1.view()
        pfns = {int(p) for lane in range(r0.n_lanes)
                for p in np.asarray(r0.machine.overlay.pfn)[lane] if p >= 0}
        for lane in range(r0.n_lanes):
            for pfn in pfns:
                assert v0.page(lane, pfn) == v1.page(lane, pfn), (
                    f"lane {lane} page {pfn:#x}")


def _occupancy(runner):
    ctr = np.asarray(runner.machine.ctr)
    instr = int(ctr[:, CTR_INSTR].sum(dtype=np.uint64))
    fused = int(ctr[:, CTR_FUSED].sum(dtype=np.uint64))
    return fused, instr


# ---------------------------------------------------------------------------
# randomized grids over the hot-subset opclasses
# ---------------------------------------------------------------------------

_R64 = ["rax", "rbx", "rcx", "rdx", "rsi", "rdi", "r8", "r9", "r10", "r11"]
_R32 = ["eax", "ebx", "ecx", "edx", "esi", "edi", "r8d", "r9d", "r10d",
        "r11d"]
_R16 = ["ax", "bx", "cx", "dx", "si", "di", "r8w", "r9w", "r10w", "r11w"]
_R8_LEGACY = ["al", "bl", "cl", "dl", "ah", "bh", "ch", "dh"]
_R8_REX = ["sil", "dil", "r8b", "r9b", "r10b", "r11b"]
_ALU = ["add", "adc", "sub", "sbb", "and", "or", "xor", "cmp", "test"]
_UNARY = ["inc", "dec", "neg", "not"]
_CC = ["o", "no", "b", "ae", "e", "ne", "be", "a", "s", "ns", "p", "np",
       "l", "ge", "le", "g"]


def _gen_hot_program(rng: random.Random, n: int = 40) -> str:
    """A random straight-line-plus-forward-branches program made entirely
    of hot-subset instructions (MOV/MOVZX/MOVSX, ALU, UNARY, LEA, SETcc,
    CMOVcc, Jcc taken and not taken, JMP, jrcxz, NOP), ending in int3."""
    lines = []

    def regpair(width):
        if width == 8:
            fam = rng.choice((_R8_LEGACY, _R8_REX))
            return rng.choice(fam), rng.choice(fam)
        pool = {64: _R64, 32: _R32, 16: _R16}[width]
        return rng.choice(pool), rng.choice(pool)

    for _ in range(n):
        kind = rng.randrange(10)
        width = rng.choice((64, 32, 16, 8))
        ra, rb = regpair(width)
        if kind == 0:
            if width == 64:
                lines.append(f"mov {ra}, {rng.getrandbits(64):#x}")
            else:
                lines.append(f"mov {ra}, {rng.getrandbits(width):#x}")
        elif kind == 1:
            lines.append(f"mov {ra}, {rb}")
        elif kind == 2:
            op = rng.choice(("movzx", "movsx"))
            dst = rng.choice(_R64 if rng.random() < 0.5 else _R32)
            src = rng.choice(_R8_REX + ["al", "bl", "cl", "dl"]
                             if rng.random() < 0.5 else _R16)
            lines.append(f"{op} {dst}, {src}")
        elif kind == 3:
            op = rng.choice(_ALU)
            if rng.random() < 0.5:
                lines.append(f"{op} {ra}, {rb}")
            else:
                imm = rng.randrange(-2**31, 2**31) if width >= 32 \
                    else rng.getrandbits(width - 1)
                lines.append(f"{op} {ra}, {imm}")
        elif kind == 4:
            lines.append(f"{rng.choice(_UNARY)} {ra}")
        elif kind == 5:
            base = rng.choice(_R64)
            idx = rng.choice([r for r in _R64 if r != "rsp"])
            scale = rng.choice((1, 2, 4, 8))
            disp = rng.randrange(-0x1000, 0x1000)
            lines.append(f"lea {rng.choice(_R64)}, "
                         f"[{base} + {idx}*{scale} + {disp}]")
        elif kind == 6:
            lines.append(f"set{rng.choice(_CC)} "
                         f"{rng.choice(_R8_LEGACY + _R8_REX)}")
        elif kind == 7:
            w = rng.choice((64, 32, 16))
            ca, cb = regpair(w)
            lines.append(f"cmov{rng.choice(_CC)} {ca}, {cb}")
        elif kind == 8:
            # forward branch (taken or not decided by live flags / rcx)
            op = rng.choice([f"j{cc}" for cc in _CC] + ["jmp", "jrcxz"])
            filler = f"{rng.choice(_UNARY)} {rng.choice(_R64)}"
            lines.extend([f"{op} 1f", filler, "1:"])
        else:
            lines.append("nop")
    lines.append("int3")
    return "\n".join(lines)


def _random_regs(rng: random.Random):
    regs = {name: rng.getrandbits(64)
            for name in ("rax", "rbx", "rcx", "rdx", "rsi", "rdi",
                         "r8", "r9", "r10", "r11")}
    # small rcx sometimes, so jrcxz goes both ways across programs
    if rng.random() < 0.5:
        regs["rcx"] = rng.randrange(4)
    return regs


@pytest.mark.parametrize("seed", range(6))
def test_fused_hot_grids_match_xla_and_oracle(seed):
    """Randomized grids over every hot-subset opclass: the fused ladder,
    the XLA ladder, and the EmuCpu oracle agree on state, rflags, rip,
    icount, and the coverage/edge bits; occupancy is 100% (all-hot code
    never retires an instruction on the XLA leg thanks to the resume
    hold)."""
    rng = random.Random(0xF05E + seed)
    asm = _gen_hot_program(rng)
    regs = _random_regs(rng)
    emu = run_emu(asm, regs=regs)
    (r0, s0), (r1, s1) = _run_pair(asm, regs=regs)
    for s in (s0, s1):
        assert all(StatusCode(int(x)) == StatusCode.CRASH for x in s)
    _assert_ladders_equal(r0, s0, r1, s1)
    g = np.asarray(r1.machine.gpr)
    rf = np.asarray(r1.machine.rflags)
    for lane in range(2):
        assert [int(v) for v in g[lane]] == list(emu.gpr)
        assert int(rf[lane]) & RF_CMP == emu.rflags & RF_CMP
        assert int(np.asarray(r1.machine.rip)[lane]) == emu.rip
        assert int(np.asarray(r1.machine.icount)[lane]) == emu.icount
    fused, instr = _occupancy(r1)
    assert instr == 2 * emu.icount
    assert fused == instr, (fused, instr)  # all-hot => 100% in-kernel


def test_fused_kernel_timeout_exact_vs_chunk():
    """In-kernel TIMEDOUT: with an instruction budget that trips in the
    middle of a hot stretch, the fused and XLA ladders stop on the same
    instruction with identical state (the kernel's limit check mirrors
    step_lane's)."""
    asm = """
        mov rax, 1
        mov rcx, 1000
    1:
        add rax, rcx
        lea rdx, [rax + rcx*4 + 7]
        xor rsi, rdx
        dec rcx
        jnz 1b
        int3
    """
    (r0, s0), (r1, s1) = _run_pair(asm, limit=137)
    assert all(StatusCode(int(x)) == StatusCode.TIMEDOUT for x in s1)
    _assert_ladders_equal(r0, s0, r1, s1)
    assert int(np.asarray(r1.machine.icount)[0]) == 137
    fused, instr = _occupancy(r1)
    assert fused == instr


@pytest.mark.parametrize("seed", range(3))
def test_fused_mem_grids_match_xla_and_oracle(seed):
    """The widened-subset acceptance grid: programs made of MEMORY-
    OPERAND forms — loads/stores through the in-kernel page walk and
    delta overlay, mem-dst ALU/SHIFT/UNARY read-modify-writes, widening
    and 2/3-operand mul, PUSH/POP/CALL/RET through the stack — execute
    ENTIRELY in-kernel: bit-exact vs the XLA ladder (state, dirty
    memory) and the EmuCpu oracle, at 100% occupancy."""
    rng = random.Random(0x3E30 + seed)
    body = []
    for _ in range(28):
        disp = rng.randrange(0, 0xE00) & ~7
        width, reg = rng.choice(
            (("qword", "rcx"), ("dword", "ecx"), ("word", "cx"),
             ("byte", "cl")))
        body.append(rng.choice([
            f"mov [rbx + {disp}], {reg}",
            f"mov {reg}, [rbx + {disp}]",
            f"mov {width} ptr [rbx + {disp}], {rng.getrandbits(7)}",
            f"add [rbx + {disp}], {reg}",
            f"xor rax, [rbx + {disp}]",
            f"cmp [rbx + {disp}], {reg}",
            f"movzx r10, {width.replace('qword', 'word')} ptr "
            f"[rbx + {disp}]" if width != "qword" else
            f"mov r10, [rbx + {disp}]",
            f"shl {width} ptr [rbx + {disp}], {rng.randrange(1, 7)}",
            f"neg {width} ptr [rbx + {disp}]",
            f"inc qword ptr [rbx + {disp}]",
            "shl rax, 3",
            f"ror rdx, {rng.randrange(1, 63)}",
            "shld rax, rdx, 11",
            "imul rdx, rax, 3",
            "mul rcx",
            "imul r9, rdx",
            f"setc byte ptr [rbx + {disp}]",
            f"cmovnz r10, qword ptr [rbx + {disp}]",
            "push rax\npop rsi",
            f"push qword ptr [rbx + {disp}]\npop r11",
            "push 0x1234\npop r10",
            "call 1f\njmp 2f\n1: add rax, 7\nret\n2:",
        ]))
    asm = (f"mov rbx, {DATA_BASE}\nmov rcx, 0x1122334455667788\n"
           f"mov r14, 5\n3:\n" + "\n".join(body)
           + "\ndec r14\njnz 3b\nint3")
    data = {DATA_BASE: bytes(0x1000)}
    emu = run_emu(asm, data=data)
    (r0, s0), (r1, s1) = _run_pair(asm, data=data)
    assert all(StatusCode(int(x)) == StatusCode.CRASH for x in s1)
    _assert_ladders_equal(r0, s0, r1, s1, check_mem=True)
    assert int(np.asarray(r1.machine.icount)[0]) == emu.icount
    g = np.asarray(r1.machine.gpr)
    assert [int(v) for v in g[0]] == list(emu.gpr)
    fused, instr = _occupancy(r1)
    assert fused == instr, (fused, instr)  # memory forms are hot now


@pytest.mark.parametrize("seed", range(3))
def test_fused_park_resume_seam_randomized(seed):
    """The acceptance seam: programs interleaving hot code (now
    including memory operands and stack ops) with genuinely NON-hot
    instructions (bswap, xchg, popcnt, bt, cqo, lahf) park mid-chunk and
    resume on the XLA path — final state including dirty memory is
    identical to the XLA-only ladder, and the fused/instruction counters
    partition exactly."""
    rng = random.Random(0x5EA9 + seed)
    cold_pool = [
        "bswap rax",
        "xchg rax, rdx",
        "popcnt r10, rax",
        "bt rax, 3",
        "cqo",
        "lahf",
    ]
    body = []
    for _ in range(24):
        if rng.random() < 0.4:
            body.append(rng.choice(cold_pool))
        else:
            body.append(rng.choice([
                f"add rax, {rng.randrange(1, 1 << 20)}",
                f"mov [rbx + {rng.randrange(0, 0xE00)}], rcx",
                f"add rax, [rbx + {rng.randrange(0, 0xE00)}]",
                "push rax", "pop rsi",
                "inc r9", "dec rdx", "xor rsi, rax",
                "lea rdi, [rax + rdx*2 + 5]",
                "cmovnz r10, rax", "setc r11b",
            ]))
    asm = (f"mov rbx, {DATA_BASE}\nmov rcx, 3\n1:\n"
           + "\n".join(body) + "\ndec rcx\njnz 1b\nint3")
    data = {DATA_BASE: bytes(0x1000)}
    emu = run_emu(asm, data=data)
    (r0, s0), (r1, s1) = _run_pair(asm, data=data)
    assert all(StatusCode(int(x)) == StatusCode.CRASH for x in s1)
    _assert_ladders_equal(r0, s0, r1, s1, check_mem=True)
    assert int(np.asarray(r1.machine.icount)[0]) == emu.icount
    fused, instr = _occupancy(r1)
    assert 0 < fused < instr  # genuinely mixed: both engines retired work
    # park attribution: every park here is a SUBSET park (cold opclass),
    # never a memory park — the split must say so
    ctr = np.asarray(r1.machine.ctr)
    assert ctr[:, CTR_PARK_SUBSET].sum() > 0
    assert ctr[:, CTR_PARK_MEM].sum() == 0
    # CTR_INSTR == icount invariant survives the fused ladder
    icount = np.asarray(r1.machine.icount)
    assert (ctr[:, CTR_INSTR] == icount.astype(np.uint32)).all()


@pytest.mark.parametrize("case", ("large2m", "fault", "overlay"))
def test_fused_walk_differential(case):
    """In-kernel page walk vs translate_vec_l, differentially: the XLA
    ladder translates through mem/paging.py, the kernel through its own
    scalar u32-limb walk — 2MiB large-page mappings, non-present holes
    (PAGE_FAULT with the exact faulting address), and overlay-shadowed
    frames (a host write into the lane overlay that loads must observe)
    all agree bit-exactly between the ladders."""
    if case == "large2m":
        from tests.asmhelper import assemble
        from wtf_tpu.mem.physmem import PhysMem
        from wtf_tpu.snapshot.synthetic import SyntheticSnapshotBuilder

        big_gva = 0x4000_0000
        code_base = 0x0001_4000_1000
        asm = f"""
            mov rbx, {big_gva}
            mov rax, [rbx + 0x1F0000]
            add rax, [rbx + 8]
            mov [rbx + 0x100], rax
            push rax
            pop rcx
            int3
        """
        b = SyntheticSnapshotBuilder()
        b.write(code_base, assemble(asm))
        b.map(0x7FFF_B000, 0x5000)              # stack
        # sibling 4K mapping so the 2MiB PS entry's PML4E/PDPTE parents
        # exist (same 1GiB region, different 2MiB region)
        b.map(big_gva + 0x20_0000, 0x1000)
        gpa = 0x0060_0000
        b.add_large_page_mapping(big_gva, gpa, 21)

        def phys_write(at, blob):
            b._phys_page(at >> 12)[at & 0xFFF:(at & 0xFFF) + len(blob)] \
                = blob

        phys_write(gpa + 0x1F0000,
                   (0x1111_2222_3333_4444).to_bytes(8, "little"))
        phys_write(gpa + 8, (0x10).to_bytes(8, "little"))
        pages, cpu = b.build(rip=code_base, rsp=0x7FFF_F000 - 0x100)
        snap = Snapshot(physmem=PhysMem.from_pages(pages), cpu=cpu)
        out = []
        for mode in ("off", "on"):
            r = Runner(snap, n_lanes=2, chunk_steps=64, fused_step=mode)
            out.append((r, r.run()))
        (r0, s0), (r1, s1) = out
        _assert_ladders_equal(r0, s0, r1, s1, check_mem=True)
        assert int(np.asarray(r1.machine.gpr)[0, 1]) \
            == 0x1111_2222_3333_4454
        fused, instr = _occupancy(r1)
        assert fused == instr  # the large-page walk stayed in-kernel
        return

    if case == "fault":
        asm = f"""
            mov rbx, {DATA_BASE}
            mov rax, [rbx]
            mov rcx, [rbx + 0x200000]
            int3
        """
        data = {DATA_BASE: b"\x55" * 0x1000}
        (r0, s0), (r1, s1) = _run_pair(asm, data=data)
        assert all(StatusCode(int(x)) == StatusCode.PAGE_FAULT
                   for x in s1)
        _assert_ladders_equal(r0, s0, r1, s1)
        for field in ("fault_gva", "fault_write"):
            assert np.array_equal(np.asarray(getattr(r0.machine, field)),
                                  np.asarray(getattr(r1.machine, field)))
        assert int(np.asarray(r1.machine.fault_gva)[0]) \
            == DATA_BASE + 0x200000
        # the park split attributes this as a MEMORY park, not subset
        ctr = np.asarray(r1.machine.ctr)
        assert ctr[:, CTR_PARK_MEM].sum() > 0
        return

    # overlay: a HOST write lands in the lane overlay (delta row); the
    # kernel's loads must read through it, and a kernel store to the
    # same page must merge with it
    asm = f"""
        mov rbx, {DATA_BASE}
        mov rax, [rbx + 0x10]
        mov [rbx + 0x18], rax
        add rax, [rbx + 0x18]
        int3
    """
    data = {DATA_BASE: bytes(0x1000)}
    results = []
    for mode in ("off", "on"):
        r = _make_runner(asm, data=data, n_lanes=2, fused_step=mode)
        view = r.view()
        for lane in range(2):
            view.virt_write(lane, DATA_BASE + 0x10,
                            (0xDEAD_BEEF_0BAD_F00D).to_bytes(8, "little"))
        r.push(view)
        results.append((r, r.run()))
    (r0, s0), (r1, s1) = results
    _assert_ladders_equal(r0, s0, r1, s1, check_mem=True)
    assert int(np.asarray(r1.machine.gpr)[0, 0]) \
        == (2 * 0xDEAD_BEEF_0BAD_F00D) & ((1 << 64) - 1)
    fused, instr = _occupancy(r1)
    assert fused == instr


def test_fused_breakpoint_park_and_bp_skip_resume():
    """An armed breakpoint inside hot code parks the lane (the kernel
    checks M_BP pre-execution like step_lane) and the post-handler
    bp_skip=1 resume executes the breakpointed instruction exactly once —
    handler counts and final state match the XLA ladder."""
    asm = """
        mov rax, 0
        mov rcx, 5
    1:
        add rax, rcx
        inc rdx
        dec rcx
        jnz 1b
        int3
    """
    hits = {}

    def make_handler(key):
        def handler(runner, view, lane):
            hits[key] = hits.get(key, 0) + 1
            # leave status BREAKPOINT and rip in place -> runner resumes
            # the lane with bp_skip=1
        return handler

    from tests.asmhelper import assemble
    from tests.emurunner import CODE_BASE

    code = assemble(asm)
    bp_off = code.index(bytes.fromhex("48ffc2"))  # the one `inc rdx`
    results = {}
    for mode in ("off", "on"):
        r = _make_runner(asm, n_lanes=2, fused_step=mode)
        r.cache.set_breakpoint(CODE_BASE + bp_off)
        status = r.run(bp_handler=make_handler(mode))
        assert all(StatusCode(int(x)) == StatusCode.CRASH for x in status)
        results[mode] = r
    assert hits["off"] == hits["on"] == 2 * 5  # per lane, per iteration
    r0, r1 = results["off"], results["on"]
    for field in ("gpr", "rip", "rflags", "icount", "cov", "edge"):
        assert np.array_equal(np.asarray(getattr(r0.machine, field)),
                              np.asarray(getattr(r1.machine, field))), field


@pytest.mark.slow
def test_fused_occupancy_demo_tlv_hot_loop():
    """The acceptance bar (PR 12): >= 95% of retired instructions
    execute in-kernel on the demo_tlv hot loop — with the page walk and
    delta-overlay probe in-kernel, the parser's memory-operand loop body
    no longer parks (measured 100%: the only parks left are the finish
    breakpoint's).

    `slow`: the demo_tlv image shapes force a second one-shot
    trace+compile of the fused executor (~20s on the 1-core CI box) on
    top of the synthetic-guest one the in-budget differentials pay;
    occupancy itself is also measured by `bench.py --fused-compare`
    (0.861, recorded in PERF.md)."""
    from wtf_tpu.harness import demo_tlv
    from wtf_tpu.interp.runner import warm_decode_cache

    payload = b"\x01\x08AAAAAAAA" * 50
    r = Runner(demo_tlv.build_snapshot(), n_lanes=2, chunk_steps=64,
               fused_step="on")
    # 4k instructions keep the interpret-mode dispatch count tier-1-cheap;
    # occupancy is a property of the instruction MIX, not the budget
    # (bench.py --fused-compare measures the same workload 5x deeper)
    r.limit = 4_000
    warm_decode_cache(r, demo_tlv.TARGET, payload)
    view = r.view()
    for lane in range(2):
        view.virt_write(lane, demo_tlv.INPUT_GVA, payload)
        view.r["gpr"][lane, 2] = np.uint64(len(payload))
    r.push(view)
    r.run()
    fused, instr = _occupancy(r)
    assert instr > 1000
    assert fused / instr >= 0.95, (fused, instr, fused / instr)


@pytest.mark.slow
def test_fused_campaign_parity_demo_tlv():
    """--fused-step=on drives a demo_tlv campaign end-to-end through
    FuzzLoop with crash/coverage parity vs off (same seeds, same
    batches).

    `slow`: two full campaigns through the interpret-mode kernel blow
    the tier-1 wall budget; the in-budget differentials above cover the
    same ladder at Runner level, and this runs in the slow tier."""
    from wtf_tpu.backend import create_backend
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.loop import FuzzLoop
    from wtf_tpu.fuzz.native_mutator import best_mangle_mutator
    from wtf_tpu.harness import demo_tlv

    def campaign(fused):
        rng = random.Random(0x77F)
        corpus = Corpus(rng=rng)
        corpus.add(b"\x01\x08AAAAAAAA" * 20 + b"\x03\x30" + b"B" * 0x30)
        backend = create_backend(
            "tpu", demo_tlv.build_snapshot(), n_lanes=4, limit=20_000,
            chunk_steps=256, overlay_slots=32,
            fused_step="on" if fused else "off")
        backend.initialize()
        demo_tlv.TARGET.init(backend)
        loop = FuzzLoop(backend, demo_tlv.TARGET,
                        best_mangle_mutator(rng, max_len=0x200), corpus)
        for _ in range(3):
            loop.run_one_batch()
        return loop, backend

    l0, b0 = campaign(False)
    l1, b1 = campaign(True)
    assert l0.stats.testcases == l1.stats.testcases
    assert l0.stats.crashes == l1.stats.crashes
    assert l0.stats.timeouts == l1.stats.timeouts
    assert b0.aggregate_coverage() == b1.aggregate_coverage()
    # the fast path genuinely carried the campaign
    fused = b1.registry.counter("device.fused_steps").value
    instr = b1.registry.counter("device.instructions").value
    assert fused > 0 and instr > 0


def test_fused_step_config_validation():
    """Config surface: bad values raise; 'auto' on the CPU platform
    resolves to the XLA ladder (the kernel-count win is a TPU property);
    'on' forces the fused ladder."""
    from wtf_tpu.harness import demo_tlv

    snap = demo_tlv.build_snapshot()
    with pytest.raises(ValueError):
        Runner(snap, n_lanes=2, fused_step="sometimes")
    assert Runner(snap, n_lanes=2, fused_step="auto").fused_enabled is False
    assert Runner(snap, n_lanes=2, fused_step="on").fused_enabled is True
