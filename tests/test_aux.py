"""Aux subsystem tests: symbolization, dirwatch, trace writers (incl.
tenet), backend translate/page-fault helpers, .cov reporting."""

import json

import pytest

from wtf_tpu.backend import create_backend
from wtf_tpu.fuzz.dirwatch import DirWatcher
from wtf_tpu.harness import demo_tlv
from wtf_tpu.symbols import Debugger


# ---------------------------------------------------------------------------
# Debugger / symbol store
# ---------------------------------------------------------------------------

def test_debugger_both_directions(tmp_path):
    store = tmp_path / "symbol-store.json"
    store.write_text(json.dumps({
        "mod!alpha": "0x1000", "mod!beta": "0x1800", "other!gamma": "0x5000",
    }))
    dbg = Debugger.load(store)
    assert len(dbg) == 3
    assert dbg.get_symbol("mod!beta") == 0x1800
    assert dbg.try_get_symbol("nope") is None
    with pytest.raises(KeyError):
        dbg.get_symbol("nope")
    # address -> nearest preceding symbol + offset (debugger.h:301-341)
    assert dbg.get_name(0x1000) == "mod!alpha"
    assert dbg.get_name(0x1004) == "mod!alpha+0x4"
    assert dbg.get_name(0x1900) == "mod!beta+0x100"
    assert dbg.get_name(0x6000) == "other!gamma+0x1000"
    assert dbg.get_name(0x10) == "0x10"  # below every symbol
    assert dbg.get_name(0x1900, style="modoff") == "mod+0x100"


def test_debugger_add_symbol_persists(tmp_path):
    store = tmp_path / "symbol-store.json"
    dbg = Debugger({}, store_path=store)
    dbg.add_symbol("mod!new", 0x4242)
    # persisted (reference AddSymbol writes through, debugger.h:92-108)
    reloaded = Debugger.load(store)
    assert reloaded.get_symbol("mod!new") == 0x4242
    assert reloaded.get_name(0x4250) == "mod!new+0xe"


# ---------------------------------------------------------------------------
# DirWatcher
# ---------------------------------------------------------------------------

def test_dirwatch_only_new_files_size_sorted(tmp_path):
    (tmp_path / "old").write_bytes(b"x")
    watcher = DirWatcher(tmp_path)
    assert watcher.poll() == []
    (tmp_path / "small").write_bytes(b"ab")
    (tmp_path / "big").write_bytes(b"abcdefgh")
    got = watcher.poll()
    assert [p.name for p in got] == ["big", "small"]  # biggest first
    assert watcher.poll() == []  # consumed


# ---------------------------------------------------------------------------
# trace writers
# ---------------------------------------------------------------------------

def _tlv_backend():
    backend = create_backend("emu", demo_tlv.build_snapshot(), limit=50_000)
    backend.initialize()
    demo_tlv.TARGET.init(backend)
    return backend


def test_tenet_trace_shape(tmp_path):
    backend = _tlv_backend()
    demo_tlv.TARGET.insert_testcase(
        backend, b"\x01\x03abc\x02\x08QWERTYUI")
    path = tmp_path / "t.tenet"
    backend.set_trace_file(path, "tenet")
    backend.run()
    lines = path.read_text().splitlines()
    assert len(lines) > 20
    # first line: full register dump, rip last (reference dump order)
    first = dict(kv.split("=") for kv in lines[0].split(",") if ":" not in kv)
    for reg in ("rax", "rbx", "rsp", "rip"):
        assert reg in first
    assert int(first["rip"], 16) == demo_tlv.CODE_GVA + 1  # after push rbp
    # the type-2 record stores a qword: some line carries an mw= entry
    mws = [ln for ln in lines if "mw=" in ln]
    assert mws, "no memory-write deltas recorded"
    addr_hex = f"mw={demo_tlv.SCRATCH_GVA:#x}:"
    assert any(addr_hex in ln and "QWERTYUI".encode().hex().upper()
               in ln for ln in mws)
    # delta lines only mention changed registers
    assert not all(ln.count("=") >= 17 for ln in lines[1:])


def test_rip_vs_cov_trace(tmp_path):
    backend = _tlv_backend()
    demo_tlv.TARGET.insert_testcase(backend, b"\x01\x03abc")
    rip_path = tmp_path / "t.rip"
    backend.set_trace_file(rip_path, "rip")
    backend.run()
    backend.restore()
    demo_tlv.TARGET.insert_testcase(backend, b"\x01\x03abc")
    cov_path = tmp_path / "t.cov"
    backend.set_trace_file(cov_path, "cov")
    backend.run()
    rips = rip_path.read_text().splitlines()
    covs = cov_path.read_text().splitlines()
    assert len(set(covs)) == len(covs)  # unique
    assert set(covs) == set(rips)       # same coverage
    assert len(rips) > len(covs)        # loop re-executions


# ---------------------------------------------------------------------------
# translate / page-fault helpers
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("backend_name", ["emu", "tpu"])
def test_virt_translate_and_pf_helper(backend_name):
    backend = create_backend(
        backend_name, demo_tlv.build_snapshot(),
        **({"n_lanes": 2} if backend_name == "tpu" else {}))
    backend.initialize()
    gpa = backend.virt_translate(demo_tlv.INPUT_GVA)
    assert gpa % 0x1000 == 0
    # same page, same frame; different mapped page, different frame
    assert backend.virt_translate(demo_tlv.INPUT_GVA + 8) == gpa + 8
    with pytest.raises(Exception):
        backend.virt_translate(0xDEAD_0000_0000)
    # reference polarity (bochscpu_backend.cc:917-999): False == the whole
    # range is already mapped, nothing to fault in
    assert not backend.page_faults_memory_if_needed(demo_tlv.INPUT_GVA, 0x1000)
    assert not backend.page_faults_memory_if_needed(demo_tlv.CODE_GVA, 4)
    # an unmapped range needs a #PF injected — but demo_tlv's snapshot has
    # no IDT, so injection is impossible and must surface loudly rather
    # than silently report "mapped" (the guest-delivery round trip is
    # covered by tests/test_usermode.py on a guest WITH an IDT)
    with pytest.raises(Exception):
        backend.page_faults_memory_if_needed(0xDEAD_0000_0000, 8)


# ---------------------------------------------------------------------------
# .cov reporting through the CLI
# ---------------------------------------------------------------------------

def test_cli_run_coverage_report(tmp_path, capsys):
    from wtf_tpu.cli import main

    covdir = tmp_path / "coverage"
    covdir.mkdir()
    (covdir / "tlv.cov").write_text(json.dumps({
        "name": "tlv",
        "addresses": [demo_tlv.CODE_GVA, demo_tlv.CODE_GVA + 1,
                      0xDEAD0000],  # one never-hit block
    }))
    case = tmp_path / "in.bin"
    case.write_bytes(b"\x01\x02ab")
    rc = main(["run", "--name", "demo_tlv", "--backend", "emu",
               "--input", str(case), "--coverage", str(covdir)])
    assert rc == 0
    assert "coverage: 2/3 listed basic blocks hit" in capsys.readouterr().out


def test_decode_pointer_matches_ntdll():
    """DecodePointer/EncodePointer (reference utils.cc:302-304): the
    rotate-xor round trip and a pinned vector."""
    from wtf_tpu.core.nt import decode_pointer, encode_pointer

    cookie = 0x00A1B2C3D4E5F607
    for value in (0, 1, 0xFFFF_FFFF_FFFF_FFFF, 0x7FFE_0000_1234_5678):
        assert decode_pointer(cookie, encode_pointer(cookie, value)) == value
    # pinned: rotr(v, 0x40 - (c & 0x3F)) ^ c computed independently
    value = 0x1122334455667788
    rot = 0x40 - (cookie & 0x3F)
    expect = (((value >> rot) | (value << (64 - rot)))
              & (1 << 64) - 1) ^ cookie
    assert decode_pointer(cookie, value) == expect


@pytest.mark.parametrize("backend_name", ["emu", "tpu"])
def test_print_registers_dump(backend_name, capsys):
    """PrintRegisters parity (backend.cc:309-332): six windbg-style rows
    over the current lane."""
    backend = create_backend(
        backend_name, demo_tlv.build_snapshot(),
        **({"n_lanes": 2} if backend_name == "tpu" else {}))
    backend.initialize()
    backend.rax(0x1122334455667788)
    backend.print_registers()
    out = capsys.readouterr().out.splitlines()
    assert len(out) == 6
    assert out[0].startswith("rax=1122334455667788")
    assert out[2].split()[0].startswith("rip=")
