"""Benchmark: fuzzing throughput of the TPU backend on the demo_tlv target.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: testcase executions per second per chip on the synthetic TLV-parser
snapshot (the reference's headline number is execs/s of its backends on its
demo snapshots; no Windows crash-dump ships with either tree, so both sides
are measured on their demo parser workloads).

vs_baseline: measured exec/s divided by a bochscpu-equivalent estimate for
the same workload.  The reference publishes only relative numbers
(bochscpu ~100x slower than KVM, README.md:291); a bochs-style interpreting
emulator sustains ~50M instr/s on one host core, and this workload executes
~250 instructions/testcase plus a full dirty-page restore, so the bochscpu
role is estimated at 50e6/250 = 200k execs/s-equivalent... that flatters
bochs (restore ignored), which is the conservative direction for us.
"""

import json
import os
import time

os.environ.setdefault("XLA_FLAGS", "")


def main():
    import random

    from wtf_tpu.backend import create_backend
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.loop import FuzzLoop
    from wtf_tpu.fuzz.mutator import MangleMutator
    from wtf_tpu.harness import demo_tlv

    n_lanes = int(os.environ.get("BENCH_LANES", "256"))
    seconds = float(os.environ.get("BENCH_SECONDS", "20"))

    snapshot = demo_tlv.build_snapshot()
    backend = create_backend("tpu", snapshot, n_lanes=n_lanes,
                             limit=100_000, chunk_steps=512)
    backend.initialize()
    demo_tlv.TARGET.init(backend)

    rng = random.Random(0x77F)
    corpus = Corpus(rng=rng)
    corpus.add(b"\x01\x04AAAA\x02\x08BBBBBBBB")
    mutator = MangleMutator(rng, max_len=0x400)
    loop = FuzzLoop(backend, demo_tlv.TARGET, mutator, corpus)

    # warmup: first batches pay XLA compilation + decode servicing
    loop.run_one_batch()
    loop.run_one_batch()

    start = time.time()
    start_count = loop.stats.testcases
    while time.time() - start < seconds:
        loop.run_one_batch()
    elapsed = time.time() - start
    execs = loop.stats.testcases - start_count
    execs_per_sec = execs / elapsed

    bochs_equiv = 200_000.0  # see module docstring
    print(json.dumps({
        "metric": "exec/s/chip (demo_tlv snapshot fuzz, coverage-guided)",
        "value": round(execs_per_sec, 1),
        "unit": "execs/s",
        "vs_baseline": round(execs_per_sec / bochs_equiv, 4),
    }))


if __name__ == "__main__":
    main()
