"""Benchmark: fuzzing throughput of the TPU backend on the demo_tlv target.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Metric: testcase executions per second per chip on the synthetic TLV-parser
snapshot (the reference's headline number is execs/s of its backends on its
demo snapshots; no Windows crash-dump ships with either tree, so both sides
are measured on their demo parser workloads).

vs_baseline: measured exec/s divided by a bochscpu-equivalent estimate for
the same workload.  The reference publishes only relative numbers
(bochscpu ~100x slower than KVM, README.md:291); a bochs-style interpreting
emulator sustains ~50M instr/s on one host core, and this workload executes
~250 instructions/testcase plus a full dirty-page restore, so the bochscpu
role is estimated at 50e6/250 = 200k execs/s-equivalent... that flatters
bochs (restore ignored), which is the conservative direction for us.

Robustness (BENCH_r02 died in TPU client init before measuring anything):
the measurement runs in a supervised subprocess with a hard timeout; on
init failure or hang it retries once, then falls back to the CPU platform.
The supervisor ALWAYS prints the one JSON line.
"""

import json
import os
import subprocess
import sys
import time

# Model fallback when the measured denominator cannot build (no g++):
# a bochs-style interpreter sustains ~50M instr/s on one core / ~250
# instr per exec = 200k exec/s.  When `_measure_bochs_equiv` succeeds the
# denominator is MEASURED instead (VERDICT r4 item 6): a minimal C++
# fetch-decode-execute interpreter (native/bochsref.cc) running the same
# snapshot bytes + same mutated testcase stream with bochs's per-
# instruction coverage-insert and per-exec restore — deliberately faster
# than real bochs (tiny decoder, flat memory, no hook chain), so the
# resulting vs_baseline is a LOWER bound for the TPU side.
BOCHS_EQUIV = 200_000.0


def _measure_bochs_equiv() -> dict | None:
    """exec/s of the C++ bochs-role interpreter on the demo_tlv workload
    (same code bytes, same mangle-mutated stream as the main measurement).
    Returns None when the native library can't build."""
    import ctypes
    import random

    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.native_mutator import best_mangle_mutator
    from wtf_tpu.harness import demo_tlv as T
    from wtf_tpu.native import build_library

    path = build_library("bochsref", ["bochsref.cc"])
    if path is None:
        return None
    lib = ctypes.CDLL(str(path))
    u64, u32, u8p = ctypes.c_uint64, ctypes.c_uint32, ctypes.POINTER(
        ctypes.c_uint8)
    lib.bochsref_create.restype = ctypes.c_void_p
    lib.bochsref_create.argtypes = [ctypes.POINTER(u64), ctypes.POINTER(u64),
                                    ctypes.POINTER(u8p), ctypes.c_int]
    lib.bochsref_campaign.argtypes = [
        ctypes.c_void_p, u64, u64, u64, u64, u64,
        u8p, ctypes.POINTER(u32), ctypes.c_int, u64, u64,
        ctypes.POINTER(u64), ctypes.POINTER(u64), ctypes.POINTER(u64)]
    lib.bochsref_destroy.argtypes = [ctypes.c_void_p]

    rsp = T.STACK_TOP - 0x1000
    stack_base = T.STACK_TOP - 0x8000
    stack = bytearray(0x9000)
    stack[rsp - stack_base:rsp - stack_base + 8] = T.FINISH_GVA.to_bytes(
        8, "little")
    spans = [
        (T.CODE_GVA, T._GUEST_CODE.ljust(0x1000, b"\xcc")),
        (T.FINISH_GVA, b"\x90\xf4".ljust(0x1000, b"\xcc")),
        (T.INPUT_GVA, bytes(T.MAX_INPUT)),
        (T.SCRATCH_GVA, bytes(0x1000)),
        (stack_base, bytes(stack)),
    ]
    bases = (u64 * len(spans))(*[s[0] for s in spans])
    sizes = (u64 * len(spans))(*[len(s[1]) for s in spans])
    bufs = [(ctypes.c_uint8 * len(s[1])).from_buffer_copy(s[1])
            for s in spans]
    datas = (u8p * len(spans))(*[ctypes.cast(b, u8p) for b in bufs])
    vm = lib.bochsref_create(bases, sizes, datas, len(spans))

    # the SAME testcase distribution as the device measurement: mangle
    # over the same seed corpus
    rng = random.Random(0x77F)
    corpus = Corpus(rng=rng)
    corpus.add(b"\x01\x04AAAA\x02\x08BBBBBBBB")
    mutator = best_mangle_mutator(rng, max_len=0x400)
    tcs = [mutator.get_new_testcase(corpus) for _ in range(2048)]
    flat = b"".join(tcs)
    tc_buf = (ctypes.c_uint8 * len(flat)).from_buffer_copy(flat)
    lens = (u32 * len(tcs))(*[len(t) for t in tcs])

    execs = u64(0)
    instr = u64(0)
    crashes = u64(0)

    def run(repeat: int) -> float:
        t0 = time.time()
        lib.bochsref_campaign(
            vm, T.CODE_GVA, rsp, T.INPUT_GVA, T.FINISH_GVA, T.SCRATCH_GVA,
            ctypes.cast(tc_buf, u8p), lens, len(tcs), 100_000, repeat,
            ctypes.byref(execs), ctypes.byref(instr), ctypes.byref(crashes))
        return time.time() - t0

    dt = run(1)                       # calibrate
    repeat = max(1, int(3.0 / max(dt, 1e-3)))
    dt = run(repeat)
    lib.bochsref_destroy(vm)
    return {
        "execs_per_s": round(execs.value / dt, 1),
        "instr_per_s": round(instr.value / dt, 1),
        "crash_frac": round(crashes.value / max(execs.value, 1), 3),
        "note": ("minimal C++ interpreter w/ per-instr coverage insert + "
                 "per-exec restore; faster than real bochs (upper bound)"),
    }


def worker() -> None:
    """The actual measurement (runs in a subprocess; may be told cpu)."""
    import random

    import numpy as np

    from wtf_tpu.backend import create_backend
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.loop import FuzzLoop
    from wtf_tpu.fuzz.native_mutator import best_mangle_mutator
    from wtf_tpu.harness import demo_tlv

    # Pin EVERY rng (the per-measurement random.Random(seed) objects below
    # are already pinned; this covers any library that reaches for the
    # module-level generators): run-to-run spread must be measurement
    # noise, not mutation-stream luck (VERDICT weak item 1).
    random.seed(0x77F)
    np.random.seed(0x77F)

    if os.environ.get("BENCH_PLATFORM") == "cpu":
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    import jax

    platform = jax.devices()[0].platform
    n_lanes = int(os.environ.get("BENCH_LANES", "4096"))
    seconds = float(os.environ.get("BENCH_SECONDS", "20"))
    if platform == "cpu":
        # degraded mode: a 1-core host can't drive wide batches; keep the
        # measurement inside the attempt budget
        n_lanes = min(n_lanes, 128)

    snapshot = demo_tlv.build_snapshot()
    # lanes are the throughput axis (per-step wall is kernel-latency
    # dominated, PERF.md); start wide and halve on allocation failure
    backend = None
    while True:
        try:
            backend = create_backend("tpu", snapshot, n_lanes=n_lanes,
                                     limit=100_000, chunk_steps=512,
                                     overlay_slots=32)
            backend.initialize()
            break
        except Exception as e:  # noqa: BLE001
            # only allocation pressure justifies shrinking the batch; any
            # other failure re-raises (the supervisor handles retries)
            msg = f"{type(e).__name__}: {e}"
            oom = ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
                   or "out of memory" in msg)
            if not oom or n_lanes <= 128:
                raise
            print(f"bench: {n_lanes} lanes OOM, halving ({msg[:120]})",
                  file=sys.stderr)
            n_lanes //= 2
    demo_tlv.TARGET.init(backend)

    rng = random.Random(0x77F)
    corpus = Corpus(rng=rng)
    corpus.add(b"\x01\x04AAAA\x02\x08BBBBBBBB")
    mutator = best_mangle_mutator(rng, max_len=0x400)
    loop = FuzzLoop(backend, demo_tlv.TARGET, mutator, corpus)

    # warmup rep: first batches pay XLA compilation + decode servicing
    loop.run_one_batch()
    loop.run_one_batch()

    # Headline runs >= 3 timed reps after the warmup; the reported value
    # is the MEDIAN and the JSON carries mean/stddev — the artifact
    # needed to tell measurement noise from real regressions (the
    # 709-vs-940 driver/builder spread question, VERDICT weak item 1).
    reps = max(int(os.environ.get("BENCH_REPS", "3")), 3)
    rep_window = seconds / reps
    rep_rates = []
    for _ in range(reps):
        start = time.time()
        start_count = loop.stats.testcases
        while time.time() - start < rep_window:
            loop.run_one_batch()
        elapsed = time.time() - start
        rep_rates.append((loop.stats.testcases - start_count) / elapsed)
    ordered = sorted(rep_rates)
    n = len(ordered)
    execs_per_sec = (ordered[n // 2] if n % 2
                     else 0.5 * (ordered[n // 2 - 1] + ordered[n // 2]))
    rep_mean = sum(rep_rates) / len(rep_rates)
    rep_stddev = (sum((r - rep_mean) ** 2 for r in rep_rates)
                  / len(rep_rates)) ** 0.5

    # headline result is complete here; the optional microbench must not be
    # able to lose it (the round-2 failure mode: die before reporting)
    denom = BOCHS_EQUIV
    denom_kind = "model"
    bochs = None
    try:
        bochs = _measure_bochs_equiv()
    except Exception as e:  # noqa: BLE001
        bochs = {"error": str(e)[:200]}
    if bochs and "execs_per_s" in bochs:
        denom = bochs["execs_per_s"]
        denom_kind = "measured"
    report = {
        "metric": "exec/s/chip (demo_tlv snapshot fuzz, coverage-guided)",
        "value": round(execs_per_sec, 1),
        "unit": "execs/s",
        "vs_baseline": round(execs_per_sec / denom, 4),
        "platform": platform,
        "lanes": n_lanes,
        # value is the MEDIAN of the reps; mean/stddev say how noisy the
        # host was when it was taken
        "headline": {
            "reps": [round(r, 1) for r in rep_rates],
            "mean": round(rep_mean, 1),
            "stddev": round(rep_stddev, 1),
            "rep_window_s": round(rep_window, 1),
        },
        "baseline_denominator": {"kind": denom_kind, "execs_per_s": denom,
                                 **({} if bochs is None else bochs)},
    }
    try:
        report["microbench"] = _microbench(snapshot)
    except Exception as e:  # noqa: BLE001
        report["microbench"] = {"error": str(e)[:200]}
    try:
        report["deep"] = _deepbench(platform)
    except Exception as e:  # noqa: BLE001
        report["deep"] = {"error": str(e)[:200]}
    try:
        report["real_pe"] = _pebench(platform)
    except Exception as e:  # noqa: BLE001
        report["real_pe"] = {"error": str(e)[:200]}
    print(json.dumps(report))


def _pebench(platform: str) -> dict:
    """Campaign throughput on REAL Windows machine code: the demo_pe
    target maps gle64.vc14.dll loader-style and fuzzes the exported
    glePolyCylinder (VERDICT r4 item 3's decode/fallback-stats-on-real-
    MSVC-code evidence, as a measured number)."""
    import random

    from wtf_tpu.backend import create_backend
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.loop import FuzzLoop
    from wtf_tpu.fuzz.native_mutator import best_mangle_mutator
    from wtf_tpu.harness import demo_pe

    if not demo_pe.available():
        return {"skipped": "census DLL not present"}
    n_lanes = 16 if platform == "cpu" else 512
    seconds = 10.0 if platform == "cpu" else 20.0
    backend = create_backend("tpu", demo_pe.build_snapshot(),
                             n_lanes=n_lanes, limit=2_000_000,
                             chunk_steps=512, overlay_slots=32)
    backend.initialize()
    demo_pe.TARGET.init(backend)
    rng = random.Random(0x9E1)
    corpus = Corpus(rng=rng)
    import struct as _st

    pts = _st.pack("<12d", *(float(k) for k in range(1, 13)))
    corpus.add(_st.pack("<Id", 4, 0.5) + pts)
    mutator = best_mangle_mutator(rng, max_len=0x200)
    loop = FuzzLoop(backend, demo_pe.TARGET, mutator, corpus)
    loop.run_one_batch()  # warmup: compile + decode the DLL paths
    c0 = loop.stats.testcases
    i0 = backend.stats["instructions"]
    f0 = backend.runner.stats["fallbacks"]
    fc0 = dict(backend.runner.stats["fallbacks_by_opclass"])
    x0 = loop.stats.crashes
    start = time.time()
    while time.time() - start < seconds:
        loop.run_one_batch()
    elapsed = time.time() - start
    execs = loop.stats.testcases - c0
    fc1 = backend.runner.stats["fallbacks_by_opclass"]
    by_class = {k: v - fc0.get(k, 0) for k, v in fc1.items()
                if v - fc0.get(k, 0) > 0}
    return {
        "workload": "gle64.vc14.dll glePolyCylinder mangle campaign",
        "execs_per_s": round(execs / elapsed, 2),
        "instr_per_s": round(
            (backend.stats["instructions"] - i0) / elapsed, 1),
        "oracle_fallbacks": backend.runner.stats["fallbacks"] - f0,
        "fallbacks_by_opclass": dict(sorted(
            by_class.items(), key=lambda kv: -kv[1])),
        "crashes": loop.stats.crashes - x0,
        "lanes": n_lanes,
        "degraded": platform == "cpu",
    }


def _deepbench(platform: str) -> dict:
    """BASELINE-config-3-shaped number (VERDICT r3 item 7): a mangle-driven
    campaign on the deep-execution target with a 10M-instruction budget per
    testcase, reporting execs/s AND instr/s.  demo_tlv's ~250-instruction
    executions measure servicing overhead; this measures interpreter
    throughput on HEVD-class execution depths (BASELINE.md configs 3-5 are
    10M-100M instr/testcase).  Mangled u32 spin counts mean most lanes run
    to the instruction budget — exactly the reference's deep-campaign
    behavior under --limit."""
    import random
    import struct

    from wtf_tpu.backend import create_backend
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.loop import FuzzLoop
    from wtf_tpu.fuzz.native_mutator import best_mangle_mutator
    from wtf_tpu.harness import demo_spin

    if platform == "cpu":
        # DEGRADED: a 1-core host interprets ~100k instr/s; a 10M budget
        # would never complete an exec inside the bench window.  Keep the
        # workload *shape* (deep spins + mangle) at a depth the host can
        # turn around, and say so in the report.
        limit, n_lanes, seconds = 200_000, 16, 15.0
    else:
        limit, n_lanes, seconds = 10_000_000, 1024, 40.0
    limit = int(os.environ.get("BENCH_DEEP_LIMIT", limit))
    n_lanes = int(os.environ.get("BENCH_DEEP_LANES", n_lanes))

    backend = create_backend("tpu", demo_spin.build_snapshot(),
                             n_lanes=n_lanes, limit=limit, chunk_steps=512,
                             overlay_slots=16)
    backend.initialize()
    demo_spin.TARGET.init(backend)
    rng = random.Random(0xD33B)
    corpus = Corpus(rng=rng)
    # Honest-number tuning (VERDICT r4 item 7): an uncapped mangled u32
    # mostly lands ABOVE the budget, so the round-4 deep number measured
    # timeout handling (timeout_frac 0.59), not interpretation.  Cap the
    # mangled spin count at 1.1x the budget: most lanes FINISH, a small
    # minority still exercises the timeout path, and instr/s measures
    # the interpreter (target timeout_frac < 0.2).
    max_iters = max(int(limit / demo_spin.INSNS_PER_ITER * 1.1), 1)
    corpus.add(struct.pack("<I", max(max_iters // 2, 1)))

    class _CappedSpin:
        def __init__(self, inner):
            self.inner = inner

        @staticmethod
        def _cap(raw: bytes) -> bytes:
            (count,) = struct.unpack("<I", raw.ljust(4, b"\x00")[:4])
            return struct.pack("<I", count % max_iters)

        def get_new_testcase(self, corp) -> bytes:
            return self._cap(self.inner.get_new_testcase(corp))

        def get_new_batch(self, corp, count: int):
            # keep the ONE-native-call batch path FuzzLoop fast-paths on
            return [self._cap(t)
                    for t in self.inner.get_new_batch(corp, count)]

        def on_new_coverage(self, testcase: bytes) -> None:
            self.inner.on_new_coverage(testcase)

    mutator = _CappedSpin(best_mangle_mutator(rng, max_len=4))
    loop = FuzzLoop(backend, demo_spin.TARGET, mutator, corpus)

    loop.run_one_batch()  # warmup: compile + decode
    i0 = backend.stats["instructions"]
    c0 = loop.stats.testcases
    t0 = loop.stats.timeouts
    start = time.time()
    while time.time() - start < seconds:
        loop.run_one_batch()
    elapsed = time.time() - start
    execs = loop.stats.testcases - c0
    instr = backend.stats["instructions"] - i0
    return {
        "workload": f"demo_spin mangle campaign, limit={limit}",
        "execs_per_s": round(execs / elapsed, 2),
        "instr_per_s": round(instr / elapsed, 1),
        "timeout_frac": round((loop.stats.timeouts - t0) / max(execs, 1), 3),
        "lanes": n_lanes,
        "limit": limit,
        "degraded": platform == "cpu",
    }


def _microbench(snapshot) -> dict:
    """Device instructions/s for a straight-line and a branchy guest
    workload, plus the per-chunk servicing floor (VERDICT round-2 item 7:
    measure before optimizing the hot path).  The warm-runner +
    chunk-timing recipe is shared with ablate.py and the linter
    (wtf_tpu/analysis/trace.py)."""
    import jax.numpy as jnp

    from wtf_tpu.analysis.trace import build_tlv_runner, timed_chunk

    out = {}
    n_lanes = int(os.environ.get("BENCH_MICRO_LANES", "1024"))
    # warm decode cache via the oracle on a long type-1 (sum loop) workload:
    # branchy (loop back-edge + record dispatch) — the realistic shape
    r = build_tlv_runner(n_lanes=n_lanes, chunk_steps=512,
                         payload=b"\x01\x08AAAAAAAA" * 100,
                         snapshot=snapshot)
    t = timed_chunk(r)
    out["branchy_instr_per_s"] = round(t["instr"] / t["warm_wall_s"], 1)
    out["chunk512_wall_s"] = round(t["warm_wall_s"], 4)
    # servicing floor: chunk call with every lane terminal (early exit) —
    # pure dispatch+transfer overhead per host<->device round trip
    from wtf_tpu.core.results import StatusCode

    m2 = r.machine
    rc = r.chunk_executor()
    t0 = time.time()
    m3 = rc(r.cache.device(), r.physmem.image,
            m2._replace(status=jnp.full_like(m2.status, int(StatusCode.OK))),
            jnp.uint64(1 << 40))
    m3.status.block_until_ready()
    out["chunk_dispatch_floor_s"] = round(time.time() - t0, 4)
    return out


# r5 step-cost microbench numbers on the 1-core CPU stand-in (PERF.md
# round-5 status) — the default `--micro-compare` baseline, so a step.py
# change can be gated on "no worse than the last recorded round" without
# hunting for a BENCH_rXX.json.
MICRO_BASELINE_R5 = {
    "chunk512_wall_s": 4.32,
    "branchy_instr_per_s": 121_500.0,
    "source": "PERF.md r5 CPU stand-in",
}


def micro_compare(baseline_path: str | None) -> None:
    """`bench.py --micro-compare [baseline.json]`: run ONLY the step-cost
    microbench (chunk512_wall_s, branchy_instr_per_s) and print one JSON
    line with the current numbers, the baseline, and the delta ratios —
    the before/after harness for step.py optimizations (u32 limb packing
    etc.).  A prior run's output (or a BENCH_rXX.json with a "microbench"
    extra) can be passed as the baseline; default is the r5 record.

    Runs on the CPU platform unless BENCH_PLATFORM=native — the recorded
    baselines are CPU stand-in numbers and cross-platform ratios would be
    meaningless."""
    if os.environ.get("BENCH_PLATFORM", "cpu") != "native":
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    baseline = dict(MICRO_BASELINE_R5)
    if baseline_path:
        with open(baseline_path) as fh:
            loaded = json.load(fh)
        for key in ("microbench", "current"):  # full bench / prior compare
            if key in loaded:
                loaded = loaded[key]
                break
        baseline = {"source": baseline_path, **{
            k: loaded[k] for k in ("chunk512_wall_s", "branchy_instr_per_s")
            if k in loaded}}

    from wtf_tpu.harness import demo_tlv

    current = _microbench(demo_tlv.build_snapshot())
    delta = {}
    if "chunk512_wall_s" in baseline:
        delta["chunk512_wall_s_ratio"] = round(
            current["chunk512_wall_s"] / baseline["chunk512_wall_s"], 4)
    if "branchy_instr_per_s" in baseline:
        delta["branchy_instr_per_s_ratio"] = round(
            current["branchy_instr_per_s"] / baseline["branchy_instr_per_s"],
            4)
    # regression := step got slower AND throughput dropped beyond noise
    regression = (delta.get("chunk512_wall_s_ratio", 1.0) > 1.10
                  and delta.get("branchy_instr_per_s_ratio", 1.0) < 0.90)
    print(json.dumps({
        "metric": "step-cost micro-compare",
        "current": current,
        "baseline": baseline,
        "delta": delta,
        "regression": regression,
    }))


def fused_compare() -> None:
    """`bench.py --fused-compare`: A/B the fused Pallas ladder
    (--fused-step=on, interp/pstep.py) against the plain XLA chunk path on
    the SAME warmed demo_tlv batch, printing one JSON line with warm
    walls, instr/s, the delta ratio, and the kernel occupancy (fraction
    of retired instructions executed in-kernel).

    Runs on the CPU platform unless BENCH_PLATFORM=native (same policy as
    --micro-compare).  On the CPU stand-in the expectation is
    parity-within-noise with NO regression gate: CPU XLA already fuses
    the step into a few fusions, so the dispatch-count win this path
    exists for is a TPU property — the TPU-side argument is the counted
    kernels-per-step reduction recorded in PERF.md."""
    if os.environ.get("BENCH_PLATFORM", "cpu") != "native":
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    import jax

    from ablate import fused_ab
    from wtf_tpu.interp.pstep import fused_available

    n_lanes = int(os.environ.get("BENCH_FUSED_LANES", "128"))
    limit = int(os.environ.get("BENCH_FUSED_LIMIT", "20000"))
    chunk = int(os.environ.get("BENCH_FUSED_CHUNK", "512"))

    if not fused_available():
        print(json.dumps({
            "metric": "fused-vs-XLA chunk compare",
            "skipped": "this jax build cannot run pallas kernels"}))
        return
    cols = fused_ab(n_lanes, limit, chunk, b"\x01\x08AAAAAAAA" * 100)
    print(json.dumps({
        "metric": "fused-vs-XLA chunk compare (demo_tlv, per-lane "
                  f"limit={limit})",
        "platform": jax.devices()[0].platform,
        "lanes": n_lanes,
        "xla": cols["off"],
        "fused": cols["on"],
        "wall_ratio_fused_over_xla": round(
            cols["on"]["warm_wall_s"] / cols["off"]["warm_wall_s"], 4),
        "note": "CPU stand-in has no regression gate (XLA CPU already "
                "fuses); the TPU argument is kernel-count per step",
    }))


def telemetry_mode(telemetry_dir: str | None = None) -> None:
    """`bench.py --telemetry [dir]`: a short instrumented campaign whose
    JSON is DERIVED FROM THE METRICS REGISTRY — the same counters and
    span totals behind the campaign heartbeat — rather than hand-rolled
    timers, so bench numbers and campaign telemetry can never disagree
    about definitions.  With a dir argument the JSONL event stream lands
    there too (summarize with tools/telemetry_report.py).

    Runs on the CPU platform unless BENCH_PLATFORM=native (same policy as
    --micro-compare: this mode is about the telemetry plumbing, not chip
    throughput)."""
    if os.environ.get("BENCH_PLATFORM", "cpu") != "native":
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    import random

    from wtf_tpu.backend import create_backend
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.loop import FuzzLoop
    from wtf_tpu.fuzz.native_mutator import best_mangle_mutator
    from wtf_tpu.harness import demo_tlv
    from wtf_tpu.telemetry import Registry, open_event_log

    registry = Registry()
    events = open_event_log(telemetry_dir)
    events.emit("run-start", subcommand="bench--telemetry")
    try:
        seconds = float(os.environ.get("BENCH_SECONDS", "10"))
        n_lanes = int(os.environ.get("BENCH_TELEM_LANES", "64"))
        chunk_steps = int(os.environ.get("BENCH_TELEM_CHUNK", "512"))
        backend = create_backend("tpu", demo_tlv.build_snapshot(),
                                 n_lanes=n_lanes, limit=100_000,
                                 chunk_steps=chunk_steps,
                                 overlay_slots=32, registry=registry,
                                 events=events)
        backend.initialize()
        demo_tlv.TARGET.init(backend)
        rng = random.Random(0x77F)
        corpus = Corpus(rng=rng)
        corpus.add(b"\x01\x04AAAA\x02\x08BBBBBBBB")
        loop = FuzzLoop(backend, demo_tlv.TARGET,
                        best_mangle_mutator(rng, max_len=0x400), corpus,
                        registry=registry, events=events, stats_every=2.0)
        loop.run_one_batch()  # warmup: XLA compile + decode servicing
        start = time.time()
        start_count = loop.stats.testcases
        while time.time() - start < seconds:
            loop.run_one_batch()
            loop._heartbeat(print_stats=False)
        elapsed = time.time() - start
        metrics = registry.dump()
        phase_seconds = metrics.get("phase.seconds", {})
        top_phases = {name: round(secs, 3)
                      for name, secs in sorted(phase_seconds.items())
                      if "/" not in name}
        report = {
            "metric": "telemetry campaign (demo_tlv, registry-derived)",
            "value": round(
                (loop.stats.testcases - start_count) / elapsed, 1),
            "unit": "execs/s",
            "elapsed_s": round(elapsed, 3),
            "phases": top_phases,
            "metrics": metrics,
        }
    finally:
        # run-end even on a failed build: the JSONL must never be
        # indistinguishable from a killed run (same invariant as cli.py)
        events.emit("run-end", metrics=registry.dump())
        events.close()
    print(json.dumps(report))


def main() -> None:
    # total budget divided across attempts so a hanging TPU init can never
    # push the final (cpu) attempt past the driver's outer timeout.  A
    # dead tunnel HANGS client init rather than erroring, so native
    # attempts get a bounded slice and a timed-out first attempt skips
    # the retry (a hung tunnel stays hung; only init errors are flaky).
    budget = float(os.environ.get("BENCH_TIMEOUT", "1800"))
    deadline = time.time() + budget
    native_tmo = min(420.0, budget / 3)
    attempts = [
        ({}, native_tmo),   # native platform (tpu when available)
        ({}, native_tmo),   # retry once: tunnel init ERRORS are flaky
        # degraded cpu fallback gets whatever the budget has left (incl.
        # the slice a skipped retry freed) — the sum never exceeds the
        # budget, so the outer driver cannot kill us before the
        # guaranteed JSON line
        ({"BENCH_PLATFORM": "cpu"}, None),
    ]
    last_err = "no attempts ran"
    native_timed_out = False
    for i, (extra_env, tmo) in enumerate(attempts):
        if i == 1 and native_timed_out:
            continue  # hung tunnel: go straight to the cpu fallback
        if tmo is None:
            tmo = max(deadline - time.time(), 60.0)
        env = dict(os.environ, **extra_env)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--worker"],
                env=env, timeout=tmo, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            last_err = f"worker timed out after {tmo}s"
            if i == 0:
                native_timed_out = True
            continue
        line = next((ln for ln in proc.stdout.splitlines()
                     if ln.startswith("{")), None)
        if proc.returncode == 0 and line:
            print(line)
            return
        last_err = (proc.stderr.strip().splitlines() or ["worker failed"])[-1]
    print(json.dumps({
        "metric": "exec/s/chip (demo_tlv snapshot fuzz, coverage-guided)",
        "value": 0.0,
        "unit": "execs/s",
        "vs_baseline": 0.0,
        "error": last_err[:500],
    }))


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker()
    elif "--micro-compare" in sys.argv:
        _args = [a for a in sys.argv[1:] if not a.startswith("--")]
        micro_compare(_args[0] if _args else None)
    elif "--fused-compare" in sys.argv:
        fused_compare()
    elif "--telemetry" in sys.argv:
        _args = [a for a in sys.argv[1:] if not a.startswith("--")]
        telemetry_mode(_args[0] if _args else None)
    else:
        main()
